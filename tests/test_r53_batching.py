"""Unit + integration tier for the per-zone Route53 change batcher
(ISSUE 6, ``agac_tpu/cloudprovider/aws/batcher.py``): coalescing
across threads, atomic-pair integrity, partial-failure fan-out (one
rejected change fails ONLY the owning items, invalidates the zone
cache exactly once, and never poisons co-batched records), the async
ticket/park path, and the tier-1 wire-call regression at bench N=6
scale (``change_resource_record_sets`` ≤ ceil(N·changes/batch_max) +
slack instead of one call per record)."""

from __future__ import annotations

import math
import threading
import time

import pytest

from agac_tpu import apis
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cloudprovider.aws.batcher import ChangeBatcher
from agac_tpu.cloudprovider.aws.cache import (
    DiscoveryCache,
    HostedZoneCache,
    RecordSetCache,
)
from agac_tpu.cloudprovider.aws.driver import _poll_batch_tickets
from agac_tpu.cloudprovider.aws.errors import AWSAPIError
from agac_tpu.cloudprovider.aws.types import (
    CHANGE_ACTION_CREATE,
    CHANGE_ACTION_UPSERT,
    Change,
    ResourceRecord,
    ResourceRecordSet,
)
from agac_tpu.controllers import (
    EndpointGroupBindingConfig,
    GlobalAcceleratorConfig,
    Route53Config,
)
from agac_tpu.cluster import FakeCluster
from agac_tpu.manager import ControllerConfig, Manager
from agac_tpu.reconcile import PendingSettleTable, SETTLE_FAILED, SETTLE_READY

from .fixtures import NLB_REGION, make_lb_service


def txt_change(name: str, value: str = '"owner"', action: str = CHANGE_ACTION_CREATE) -> Change:
    return Change(
        action,
        ResourceRecordSet(
            name=name, type="TXT", ttl=300,
            resource_records=[ResourceRecord(value)],
        ),
    )


class RecordingBackend:
    """Commit sink capturing (zone, changes) per wire call; scripted
    failures by call index or by a predicate on the merged changes."""

    def __init__(self):
        self.calls: list[tuple[str, list[Change]]] = []
        self.fail_when = None  # fn(zone, changes) -> Exception | None
        self.lock = threading.Lock()

    def commit(self, zone_id, changes):
        with self.lock:
            self.calls.append((zone_id, list(changes)))
        if self.fail_when is not None:
            err = self.fail_when(zone_id, changes)
            if err is not None:
                raise err


class TestChangeBatcherUnit:
    def test_concurrent_submissions_coalesce_into_one_wire_call(self):
        backend = RecordingBackend()
        batcher = ChangeBatcher(max_changes=100, linger=0.15)
        results = []

        def submit(i):
            batcher.submit(
                "/hostedzone/Z1",
                [txt_change(f"r{i}.example.com"), txt_change(f"a{i}.example.com")],
                backend.commit,
            )
            results.append(i)

        threads = [threading.Thread(target=submit, args=(i,)) for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert len(results) == 5
        assert len(backend.calls) == 1, "five submissions, ONE wire call"
        zone, changes = backend.calls[0]
        assert zone == "/hostedzone/Z1" and len(changes) == 10
        stats = batcher.stats()
        assert stats["wire_calls"] == 1 and stats["submissions"] == 5
        assert stats["flushes"]["linger"] == 1

    def test_zones_batch_independently(self):
        backend = RecordingBackend()
        batcher = ChangeBatcher(max_changes=100, linger=0.1)
        threads = [
            threading.Thread(
                target=batcher.submit,
                args=(f"/hostedzone/Z{i % 2}", [txt_change(f"r{i}.ex.com")], backend.commit),
            )
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert len(backend.calls) == 2
        assert {zone for zone, _ in backend.calls} == {
            "/hostedzone/Z0", "/hostedzone/Z1"
        }

    def test_full_batch_cuts_linger_short(self):
        backend = RecordingBackend()
        batcher = ChangeBatcher(max_changes=4, linger=30.0)  # linger would hang
        threads = [
            threading.Thread(
                target=batcher.submit,
                args=("/hostedzone/Z1",
                      [txt_change(f"r{i}.ex.com"), txt_change(f"a{i}.ex.com")],
                      backend.commit),
            )
            for i in range(2)
        ]
        start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert time.monotonic() - start < 10, "full batch must not wait out linger"
        assert len(backend.calls) == 1 and len(backend.calls[0][1]) == 4
        assert batcher.stats()["flushes"]["full"] == 1

    def test_submission_never_splits_across_wire_calls(self):
        """The atomic TXT+A pair: a submission that does not fit the
        forming batch starts a new one instead of being split."""
        backend = RecordingBackend()
        batcher = ChangeBatcher(max_changes=3, linger=0.1)
        threads = [
            threading.Thread(
                target=batcher.submit,
                args=("/hostedzone/Z1",
                      [txt_change(f"r{i}.ex.com"), txt_change(f"a{i}.ex.com")],
                      backend.commit),
            )
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert len(backend.calls) == 2
        for _, changes in backend.calls:
            assert len(changes) == 2, "each pair intact in its own call"

    def test_partial_failure_fans_out_to_owning_item_only(self):
        """InvalidChangeBatch on a co-batched call: the batch is
        atomic at AWS, so the batcher degrades to per-submission
        commits — healthy submissions land, the owning item alone gets
        the error, and the zone cache is invalidated exactly once."""
        backend = RecordingBackend()
        invalidations = []
        folded = []

        def fail_bad_record(zone, changes):
            if any("bad." in c.record_set.name for c in changes):
                return AWSAPIError("InvalidChangeBatch", "record exists")
            return None

        backend.fail_when = fail_bad_record
        batcher = ChangeBatcher(max_changes=100, linger=0.15)
        errors: dict[str, Exception | None] = {}

        def submit(name):
            try:
                batcher.submit(
                    "/hostedzone/Z1", [txt_change(f"{name}.ex.com")],
                    backend.commit,
                    fold=lambda zone, changes: folded.append(list(changes)),
                    invalidate=invalidations.append,
                )
                errors[name] = None
            except Exception as err:
                errors[name] = err

        threads = [
            threading.Thread(target=submit, args=(name,))
            for name in ("good1", "bad", "good2")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert errors["good1"] is None and errors["good2"] is None
        assert isinstance(errors["bad"], AWSAPIError)
        assert errors["bad"].code == "InvalidChangeBatch"
        # the zone snapshot was dropped ONCE for the whole batch
        assert invalidations == ["/hostedzone/Z1"]
        # write-through folded only the committed sub-batches
        committed = {c.record_set.name for changes in folded for c in changes}
        assert committed == {"good1.ex.com", "good2.ex.com"}
        stats = batcher.stats()
        assert stats["split_commits"] == 1
        assert stats["flushes"]["split"] == 2  # two healthy singles landed

    def test_whole_batch_failure_fails_every_owner_without_invalidate(self):
        backend = RecordingBackend()
        backend.fail_when = lambda zone, changes: AWSAPIError(
            "ThrottlingException", "slow down"
        )
        invalidations = []
        batcher = ChangeBatcher(max_changes=100, linger=0.1)
        errors = []

        def submit(i):
            try:
                batcher.submit(
                    "/hostedzone/Z1", [txt_change(f"r{i}.ex.com")],
                    backend.commit, invalidate=invalidations.append,
                )
            except Exception as err:
                errors.append(err)

        threads = [threading.Thread(target=submit, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert len(errors) == 3
        assert all(e.code == "ThrottlingException" for e in errors)
        # a throttle says nothing about snapshot truth: no invalidate,
        # and no split retries hammering the throttled service
        assert invalidations == []
        assert len(backend.calls) == 1

    def test_async_ticket_resolves_and_polls(self):
        backend = RecordingBackend()
        batcher = ChangeBatcher(max_changes=100, linger=0.1)
        tickets = {}
        lead = threading.Thread(
            target=lambda: tickets.__setitem__(
                "lead",
                batcher.submit_async(
                    "/hostedzone/Z1", [txt_change("lead.ex.com")], backend.commit
                ),
            ),
        )
        lead.start()
        time.sleep(0.02)  # the leader is lingering: join its batch
        joiner = batcher.submit_async(
            "/hostedzone/Z1", [txt_change("join.ex.com")], backend.commit
        )
        assert not joiner.done(), "joiner ticket pends until the leader commits"
        assert _poll_batch_tickets([joiner]) == {}
        lead.join(5)
        assert joiner.wait(5)
        assert _poll_batch_tickets([joiner]) == {joiner: SETTLE_READY}
        assert tickets["lead"].state() == "ready"
        assert len(backend.calls) == 1 and len(backend.calls[0][1]) == 2

    def test_failed_ticket_polls_failed(self):
        backend = RecordingBackend()
        backend.fail_when = lambda zone, changes: AWSAPIError(
            "InvalidChangeBatch", "nope"
        )
        batcher = ChangeBatcher(max_changes=100, linger=0.0)
        ticket = batcher.submit_async(
            "/hostedzone/Z1", [txt_change("r.ex.com")], backend.commit
        )
        assert ticket.done() and ticket.state() == "failed"
        assert _poll_batch_tickets([ticket]) == {ticket: SETTLE_FAILED}


class TestDriverBatching:
    def _driver(self, backend, batcher, **kwargs):
        return AWSDriver(backend, backend, backend, change_batcher=batcher, **kwargs)

    def test_concurrent_ensures_share_one_wire_call_with_write_through(self):
        backend = FakeAWSBackend(quota_accelerators=10)
        zone = backend.add_hosted_zone("ex.com")
        batcher = ChangeBatcher(max_changes=100, linger=0.15)
        records = RecordSetCache(ttl=300.0)
        driver = self._driver(backend, batcher, record_cache=records)
        for i in range(2):
            lb = f"lb{i}"
            host = f"bench{i}-0123456789abcdef.elb.us-west-2.amazonaws.com"
            backend.add_load_balancer(lb, NLB_REGION, host)
            svc = make_lb_service(name=f"svc{i}", hostname=host)
            driver.ensure_global_accelerator_for_service(
                svc, svc.status.load_balancer.ingress[0], "c", lb, NLB_REGION
            )

        def ensure(i):
            host = f"bench{i}-0123456789abcdef.elb.us-west-2.amazonaws.com"
            svc = make_lb_service(name=f"svc{i}", hostname=host)
            created, retry = driver.ensure_route53_for_service(
                svc, svc.status.load_balancer.ingress[0],
                [f"app{i}.ex.com"], "c",
            )
            assert created and retry == 0

        threads = [threading.Thread(target=ensure, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        wire_calls = [c for c in backend.calls if c[0] == "ChangeResourceRecordSets"]
        assert len(wire_calls) == 1, "two TXT+A pairs, one wire call"
        names = {(r.name, r.type) for r in backend.records_in_zone(zone.id)}
        assert names == {
            ("app0.ex.com.", "TXT"), ("app0.ex.com.", "A"),
            ("app1.ex.com.", "TXT"), ("app1.ex.com.", "A"),
        }
        # write-through: the committed batch is visible in the zone
        # snapshot without another wire read
        lists_before = sum(
            1 for c in backend.calls if c[0] == "ListResourceRecordSets"
        )
        snapshot = driver._list_record_sets(zone.id)
        assert {(r.name, r.type) for r in snapshot} >= names
        assert lists_before == sum(
            1 for c in backend.calls if c[0] == "ListResourceRecordSets"
        )


def test_manager_fleet_wire_call_regression_at_bench_scale():
    """The tier-1 regression the bench proves at N=1,200: at bench N=6
    scale, a converging fleet's ``change_resource_record_sets`` wire
    calls stay ≤ ceil(total_changes / batch_max) + slack — instead of
    the one-call-per-record legacy (6 calls for 6 services).  Items
    enqueue together and their accelerators pre-exist, so the ensures
    land inside one linger window per zone."""
    n = 6
    aws = FakeAWSBackend(quota_accelerators=n + 5)
    cluster = FakeCluster()
    zone = aws.add_hosted_zone("budget.example.com")
    batcher = ChangeBatcher(max_changes=100, linger=0.25)
    settle = PendingSettleTable()
    plane = dict(
        discovery_cache=DiscoveryCache(ttl=300.0),
        zone_cache=HostedZoneCache(ttl=300.0),
        record_cache=RecordSetCache(ttl=300.0),
        change_batcher=batcher,
        settle_table=settle,
    )
    driver = AWSDriver(aws, aws, aws, **plane)
    hostnames = []
    for i in range(n):
        lb = f"lb{i}"
        host = f"bench{i}-0123456789abcdef.elb.us-west-2.amazonaws.com"
        aws.add_load_balancer(lb, NLB_REGION, host)
        svc = make_lb_service(name=f"svc{i}", hostname=host)
        # the accelerators pre-exist: the measured phase is the
        # Route53 wave, arriving together like a converged GA cohort
        driver.ensure_global_accelerator_for_service(
            svc, svc.status.load_balancer.ingress[0], "default", lb, NLB_REGION
        )
        svc.metadata.annotations[apis.ROUTE53_HOSTNAME_ANNOTATION] = (
            f"svc{i}.budget.example.com"
        )
        hostnames.append(f"svc{i}.budget.example.com")
        cluster.create("Service", svc)

    before = sum(1 for c in aws.calls if c[0] == "ChangeResourceRecordSets")
    stop = threading.Event()
    config = ControllerConfig(
        global_accelerator=GlobalAcceleratorConfig(
            workers=4, queue_qps=1000.0, queue_burst=1000
        ),
        route53=Route53Config(workers=4, queue_qps=1000.0, queue_burst=1000),
        endpoint_group_binding=EndpointGroupBindingConfig(workers=1),
        settle_poll_interval=0.05,
    )
    manager = Manager(resync_period=10_000.0)
    manager.run(
        cluster, config, stop,
        cloud_factory=lambda region: AWSDriver(
            aws, aws, aws, accelerator_missing_retry=0.1, **plane
        ),
        block=False,
        settle_table=settle,
    )
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            names = {(r.name, r.type) for r in aws.records_in_zone(zone.id)}
            if len(names) == 2 * n:
                break
            time.sleep(0.05)
        else:
            pytest.fail(
                f"fleet did not converge: {len(aws.records_in_zone(zone.id))}/{2*n} records"
            )
    finally:
        stop.set()
    wire_calls = (
        sum(1 for c in aws.calls if c[0] == "ChangeResourceRecordSets") - before
    )
    # 6 pairs = 12 changes; batch_max 100 → ceil(12/100) = 1 ideal;
    # slack 2 admits worker-interleaving generations
    ceiling = math.ceil(2 * n / 100) + 2
    assert wire_calls <= ceiling, (
        f"{wire_calls} ChangeResourceRecordSets calls for {n} services "
        f"(ceiling {ceiling}); batching regressed toward one-call-per-record"
    )
    stats = batcher.stats()
    assert stats["wire_calls"] == wire_calls
    assert max(stats["batch_sizes"]) >= 4, "no multi-item batch ever formed"
