"""Webhook tests — the full HTTP round trip like the reference's
``pkg/webhoook/webhook_test.go`` (allow on weight change, deny on ARN
change, content-type and body validation), against a live server on an
ephemeral port."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from agac_tpu.apis.endpointgroupbinding import (
    EndpointGroupBinding,
    EndpointGroupBindingSpec,
    ServiceReference,
)
from agac_tpu.cluster import ObjectMeta
from agac_tpu.cluster.serde import to_wire
from agac_tpu.webhook import make_server, validate


def binding_wire(arn="arn:aws:ga::123:eg/1", weight=None):
    """The shared fixture-object builder (the ``pkg/fixture`` analog)."""
    obj = EndpointGroupBinding(
        metadata=ObjectMeta(name="test", namespace="default"),
        spec=EndpointGroupBindingSpec(
            endpoint_group_arn=arn,
            weight=weight,
            service_ref=ServiceReference(name="svc"),
        ),
    )
    return to_wire(obj)


def review(operation="UPDATE", old=None, new=None, kind="EndpointGroupBinding"):
    request = {
        "uid": "test-uid-1",
        "kind": {"group": "operator.h3poteto.dev", "version": "v1alpha1", "kind": kind},
        "operation": operation,
    }
    if old is not None:
        request["oldObject"] = old
    if new is not None:
        request["object"] = new
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": request,
    }


class TestValidator:
    def test_weight_change_allowed(self):
        response = validate(
            review(old=binding_wire(weight=50), new=binding_wire(weight=128))
        )
        assert response["response"]["allowed"] is True
        assert response["response"]["status"]["message"] == "valid"
        assert response["response"]["uid"] == "test-uid-1"

    def test_arn_change_denied(self):
        response = validate(
            review(old=binding_wire(arn="arn:a"), new=binding_wire(arn="arn:b"))
        )
        assert response["response"]["allowed"] is False
        assert response["response"]["status"]["code"] == 403
        assert "immutable" in response["response"]["status"]["message"]

    def test_create_allowed_without_old_object(self):
        response = validate(review(operation="CREATE", new=binding_wire()))
        assert response["response"]["allowed"] is True

    def test_update_without_old_object_allowed(self):
        response = validate(review(new=binding_wire()))
        assert response["response"]["allowed"] is True

    def test_wrong_kind_denied_400(self):
        response = validate(review(kind="Service", old=binding_wire(), new=binding_wire()))
        assert response["response"]["allowed"] is False
        assert response["response"]["status"]["code"] == 400


@pytest.fixture
def server():
    srv = make_server(0)  # ephemeral port
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def post(url, body, content_type="application/json"):
    request = urllib.request.Request(
        url,
        data=body if isinstance(body, bytes) else json.dumps(body).encode(),
        headers={"Content-Type": content_type},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=5) as response:
            return response.status, response.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


class TestServer:
    def test_healthz(self, server):
        with urllib.request.urlopen(f"{server}/healthz", timeout=5) as response:
            assert response.status == 200

    def test_round_trip_deny(self, server):
        status, body = post(
            f"{server}/validate-endpointgroupbinding",
            review(old=binding_wire(arn="arn:a"), new=binding_wire(arn="arn:b")),
        )
        assert status == 200
        payload = json.loads(body)
        assert payload["response"]["allowed"] is False
        assert payload["response"]["status"]["code"] == 403

    def test_round_trip_allow(self, server):
        status, body = post(
            f"{server}/validate-endpointgroupbinding",
            review(old=binding_wire(weight=1), new=binding_wire(weight=2)),
        )
        assert status == 200
        assert json.loads(body)["response"]["allowed"] is True

    def test_wrong_content_type_400(self, server):
        status, body = post(
            f"{server}/validate-endpointgroupbinding",
            review(new=binding_wire()),
            content_type="text/plain",
        )
        assert status == 400
        assert b"invalid Content-Type" in body

    def test_empty_body_400(self, server):
        status, body = post(f"{server}/validate-endpointgroupbinding", b"")
        assert status == 400
        assert b"empty body" in body

    def test_missing_request_400(self, server):
        status, body = post(f"{server}/validate-endpointgroupbinding", {"kind": "AdmissionReview"})
        assert status == 400
        assert b"empty request" in body

    def test_unknown_path_404(self, server):
        status, _ = post(f"{server}/other", {"x": 1})
        assert status == 404


class TestTLS:
    """Live HTTPS: the webhook serves with TLS and hot-reloads a
    rotated certificate without a restart (cert-manager renews certs
    in place; the reference serves the stale cert until pod restart)."""

    @staticmethod
    def gen_cert(directory, cn):
        import subprocess

        cert = directory / f"{cn}.crt"
        key = directory / f"{cn}.key"
        subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048",
                "-keyout", str(key), "-out", str(cert),
                "-days", "1", "-nodes", "-subj", f"/CN={cn}",
            ],
            check=True,
            capture_output=True,
        )
        return cert.read_bytes(), key.read_bytes()

    @pytest.fixture
    def tls_server(self, tmp_path):
        cert1, key1 = self.gen_cert(tmp_path, "one.example")
        cert_file, key_file = tmp_path / "tls.crt", tmp_path / "tls.key"
        cert_file.write_bytes(cert1)
        key_file.write_bytes(key1)
        srv = make_server(0, str(cert_file), str(key_file))
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        yield srv.server_address[1], cert_file, key_file, tmp_path
        srv.shutdown()
        srv.server_close()

    @staticmethod
    def served_cn(port):
        import socket
        import ssl as ssl_mod

        context = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
        context.check_hostname = False
        context.verify_mode = ssl_mod.CERT_NONE
        with socket.create_connection(("127.0.0.1", port), timeout=5) as sock:
            # server_hostname supplies SNI, like the kube-apiserver does
            with context.wrap_socket(sock, server_hostname="webhook.svc") as tls:
                der = tls.getpeercert(binary_form=True)
        import subprocess

        out = subprocess.run(
            ["openssl", "x509", "-inform", "der", "-noout", "-subject"],
            input=der,
            check=True,
            capture_output=True,
        ).stdout.decode()
        return out.strip().rsplit("CN", 1)[-1].lstrip("= ")

    def test_serves_https_and_healthz(self, tls_server):
        import ssl as ssl_mod

        port, *_ = tls_server
        context = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
        context.check_hostname = False
        context.verify_mode = ssl_mod.CERT_NONE
        with urllib.request.urlopen(
            f"https://127.0.0.1:{port}/healthz", timeout=5, context=context
        ) as response:
            assert response.status == 200

    def test_bad_pair_at_startup_fails_fast(self, tmp_path):
        import ssl as ssl_mod

        cert1, _ = self.gen_cert(tmp_path, "one.example")
        _, key2 = self.gen_cert(tmp_path, "two.example")
        cert_file, key_file = tmp_path / "tls.crt", tmp_path / "tls.key"
        cert_file.write_bytes(cert1)
        key_file.write_bytes(key2)  # mismatched pair
        with pytest.raises(ssl_mod.SSLError):
            make_server(0, str(cert_file), str(key_file))

    def test_rotated_cert_served_without_restart(self, tls_server):
        port, cert_file, key_file, tmp_path = tls_server
        assert self.served_cn(port) == "one.example"

        cert2, key2 = self.gen_cert(tmp_path, "two.example")
        cert_file.write_bytes(cert2)
        key_file.write_bytes(key2)
        assert self.served_cn(port) == "two.example"

        # half-written rotation: key doesn't match cert — keep serving
        # the previous pair rather than failing handshakes
        cert3, _ = self.gen_cert(tmp_path, "three.example")
        cert_file.write_bytes(cert3)
        assert self.served_cn(port) == "two.example"
