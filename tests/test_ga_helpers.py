"""Global Accelerator pure-helper tests, mirroring the reference's
``pkg/cloudprovider/aws/global_accelerator_test.go`` tables (listener
protocol/port drift, listener derivation incl. the ALB listen-ports
annotation) plus tag/name helpers."""


from agac_tpu import apis
from agac_tpu.cluster import (
    Ingress,
    IngressBackend,
    IngressServiceBackend,
    ObjectMeta,
    Service,
    ServiceBackendPort,
    ServicePort,
)
from agac_tpu.cluster.objects import (
    HTTPIngressPath,
    HTTPIngressRuleValue,
    IngressRule,
    IngressSpec,
    ServiceSpec,
)
from agac_tpu.cloudprovider.aws import EndpointGroup, Listener, LoadBalancer, PortRange
from agac_tpu.cloudprovider.aws.driver import (
    accelerator_name,
    accelerator_tags_from_annotations,
    endpoint_contains_lb,
    listener_for_ingress,
    listener_for_service,
    listener_port_changed_from_service,
    listener_protocol_changed_from_ingress,
    listener_protocol_changed_from_service,
    tags_contains_all_values,
)
from agac_tpu.cloudprovider.aws.types import EndpointDescription, Tag


def svc_with_ports(*ports):
    return Service(
        metadata=ObjectMeta(name="svc", namespace="default"),
        spec=ServiceSpec(
            type="LoadBalancer",
            ports=[ServicePort(name=f"p{i}", protocol=proto, port=port) for i, (proto, port) in enumerate(ports)],
        ),
    )


class TestListenerProtocolChanged:
    def test_unchanged_single_udp(self):
        listener = Listener(listener_arn="sample", protocol="UDP")
        assert not listener_protocol_changed_from_service(listener, svc_with_ports(("UDP", 53)))

    def test_unchanged_multiple_tcp(self):
        listener = Listener(listener_arn="sample", protocol="TCP")
        assert not listener_protocol_changed_from_service(
            listener, svc_with_ports(("TCP", 80), ("TCP", 443))
        )

    def test_unchanged_mixed_protocols_last_wins(self):
        # [UDP, TCP] resolves to TCP (the reference's loop keeps the
        # last port's protocol, global_accelerator.go:498-510)
        listener = Listener(listener_arn="sample", protocol="TCP")
        assert not listener_protocol_changed_from_service(
            listener, svc_with_ports(("UDP", 53), ("TCP", 80))
        )

    def test_changed_single(self):
        listener = Listener(listener_arn="sample", protocol="TCP")
        assert listener_protocol_changed_from_service(listener, svc_with_ports(("UDP", 53)))

    def test_changed_multiple_udp(self):
        listener = Listener(listener_arn="sample", protocol="TCP")
        assert listener_protocol_changed_from_service(
            listener, svc_with_ports(("UDP", 53), ("UDP", 123))
        )

    def test_ingress_listener_must_be_tcp(self):
        ing = Ingress(metadata=ObjectMeta(name="i", namespace="default"))
        assert listener_protocol_changed_from_ingress(Listener(protocol="UDP"), ing)
        assert not listener_protocol_changed_from_ingress(Listener(protocol="TCP"), ing)


class TestListenerPortChanged:
    def listener(self, *ports):
        return Listener(port_ranges=[PortRange(p, p) for p in ports])

    def test_unchanged(self):
        assert not listener_port_changed_from_service(
            self.listener(80, 443), svc_with_ports(("TCP", 80), ("TCP", 443))
        )

    def test_port_added(self):
        assert listener_port_changed_from_service(
            self.listener(80), svc_with_ports(("TCP", 80), ("TCP", 443))
        )

    def test_port_removed(self):
        assert listener_port_changed_from_service(
            self.listener(80, 443), svc_with_ports(("TCP", 80))
        )

    def test_port_swapped(self):
        assert listener_port_changed_from_service(
            self.listener(80), svc_with_ports(("TCP", 8080))
        )


class TestListenerForService:
    def test_ports_and_protocol(self):
        ports, protocol = listener_for_service(svc_with_ports(("TCP", 80), ("TCP", 443)))
        assert ports == [80, 443]
        assert protocol == "TCP"

    def test_udp(self):
        ports, protocol = listener_for_service(svc_with_ports(("UDP", 53)))
        assert ports == [53]
        assert protocol == "UDP"


class TestListenerForIngress:
    def make_ingress(self, annotations=None, default_port=None, rule_ports=()):
        spec = IngressSpec()
        if default_port:
            spec.default_backend = IngressBackend(
                service=IngressServiceBackend(name="d", port=ServiceBackendPort(number=default_port))
            )
        if rule_ports:
            spec.rules = [
                IngressRule(
                    host="example.com",
                    http=HTTPIngressRuleValue(
                        paths=[
                            HTTPIngressPath(
                                path="/",
                                backend=IngressBackend(
                                    service=IngressServiceBackend(
                                        name="s", port=ServiceBackendPort(number=p)
                                    )
                                ),
                            )
                            for p in rule_ports
                        ]
                    ),
                )
            ]
        return Ingress(
            metadata=ObjectMeta(name="ing", namespace="default", annotations=annotations or {}),
            spec=spec,
        )

    def test_listen_ports_annotation_wins(self):
        ing = self.make_ingress(
            annotations={apis.ALB_LISTEN_PORTS_ANNOTATION: '[{"HTTP": 80}, {"HTTPS": 443}]'},
            rule_ports=(8080,),
        )
        ports, protocol = listener_for_ingress(ing)
        assert ports == [80, 443]
        assert protocol == "TCP"

    def test_bad_annotation_json_yields_empty(self):
        ing = self.make_ingress(
            annotations={apis.ALB_LISTEN_PORTS_ANNOTATION: "not-json"}, rule_ports=(8080,)
        )
        ports, _ = listener_for_ingress(ing)
        assert ports == []

    def test_default_backend_and_rules(self):
        ing = self.make_ingress(default_port=9000, rule_ports=(80, 8080))
        ports, _ = listener_for_ingress(ing)
        assert ports == [9000, 80, 8080]


def test_endpoint_contains_lb():
    lb = LoadBalancer(load_balancer_arn="arn:aws:elb:us-west-2::lb/x")
    eg = EndpointGroup(endpoint_descriptions=[EndpointDescription(endpoint_id="arn:aws:elb:us-west-2::lb/x")])
    assert endpoint_contains_lb(eg, lb)
    assert not endpoint_contains_lb(EndpointGroup(), lb)


def test_tags_contains_all_values():
    tags = [Tag("a", "1"), Tag("b", "2"), Tag("extra", "x")]
    assert tags_contains_all_values(tags, {"a": "1", "b": "2"})
    assert not tags_contains_all_values(tags, {"a": "1", "missing": "z"})
    assert not tags_contains_all_values(tags, {"a": "wrong"})


def test_accelerator_name_annotation_override():
    svc = svc_with_ports(("TCP", 80))
    assert accelerator_name("service", svc) == "service-default-svc"
    svc.metadata.annotations[apis.AWS_GLOBAL_ACCELERATOR_NAME_ANNOTATION] = "custom"
    assert accelerator_name("service", svc) == "custom"


def test_accelerator_tags_parse_skips_malformed():
    svc = svc_with_ports(("TCP", 80))
    svc.metadata.annotations[apis.AWS_GLOBAL_ACCELERATOR_TAGS_ANNOTATION] = (
        "env=prod,bad,team=infra,also=bad=worse"
    )
    tags = accelerator_tags_from_annotations(svc)
    assert [(t.key, t.value) for t in tags] == [("env", "prod"), ("team", "infra")]
