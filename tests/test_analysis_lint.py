"""Fixture tests for the controller invariant linter
(``agac_tpu/analysis/lint.py``): every shipped rule fires exactly once
on a seeded violation, stays quiet on the compliant twin, and the
suppression contract (justification mandatory) holds.  The final test
pins the acceptance bar: the linter runs clean over this repo itself —
the same invocation as ``make lint-invariants`` and the CI
``invariants`` job.
"""

from __future__ import annotations

import pathlib
import textwrap

from agac_tpu.analysis.lint import (
    lint_paths,
    lint_source,
    parse_ci_installed,
)
from agac_tpu.analysis.rules import RULES

REPO = pathlib.Path(__file__).resolve().parent.parent
INSTALLED = frozenset({"yaml", "pytest"})


def run(src: str, path: str = "pkg/module.py", installed=INSTALLED):
    return lint_source(textwrap.dedent(src), pathlib.Path(path), installed)


def only(violations, rule):
    assert [v.rule for v in violations] == [rule], violations
    return violations[0]


# ---------------------------------------------------------------------------
# raw-backend-call
# ---------------------------------------------------------------------------


class TestRawBackendCall:
    def test_backend_import_in_controller_fires_once(self):
        v = only(
            run(
                "from agac_tpu.cloudprovider.aws.fake_backend import FakeAWSBackend\n",
                path="agac_tpu/controllers/bad.py",
            ),
            "raw-backend-call",
        )
        assert "fake_backend" in v.message and v.line == 1

    def test_raw_handle_op_in_controller_fires_once(self):
        v = only(
            run(
                """
                def reconcile_thing(cloud, arn) -> "Result":
                    return cloud.ga.describe_accelerator(arn)
                """,
                path="agac_tpu/controllers/bad.py",
            ),
            "raw-backend-call",
        )
        assert "ga.describe_accelerator" in v.message

    def test_driver_wrapper_call_is_clean(self):
        # the driver mirrors op names as shaped wrappers; calling the
        # driver is the sanctioned path
        assert (
            run(
                """
                def reconcile_thing(cloud, arn) -> "Result":
                    return cloud.describe_endpoint_group(arn)
                """,
                path="agac_tpu/controllers/good.py",
            )
            == []
        )

    def test_rule_is_scoped_to_controllers(self):
        # tests construct backends directly by design
        assert (
            run(
                "from agac_tpu.cloudprovider.aws.fake_backend import FakeAWSBackend\n",
                path="tests/test_something.py",
            )
            == []
        )


# ---------------------------------------------------------------------------
# bare-lock-acquire
# ---------------------------------------------------------------------------


class TestBareLockAcquire:
    def test_bare_acquire_fires_once(self):
        v = only(
            run(
                """
                def f(self):
                    self._lock.acquire()
                    self.n += 1
                """
            ),
            "bare-lock-acquire",
        )
        assert "with _lock:" in v.message

    def test_with_statement_is_clean(self):
        assert (
            run(
                """
                def f(self):
                    with self._lock:
                        self.n += 1
                """
            )
            == []
        )

    def test_non_lockish_receiver_is_clean(self):
        # TokenBucket.acquire-style blocking facades are not locks
        assert run("def f(bucket):\n    bucket.acquire()\n") == []


# ---------------------------------------------------------------------------
# blocking-reconcile
# ---------------------------------------------------------------------------


class TestBlockingReconcile:
    def test_sleep_in_process_func_fires_once(self):
        v = only(
            run(
                """
                import time

                def process_create_or_update(obj):
                    time.sleep(1.0)
                    return obj
                """
            ),
            "blocking-reconcile",
        )
        assert "process_create_or_update" in v.message

    def test_injected_sleep_seam_is_clean(self):
        # a deadline-bounded injected sleep (driver pattern) is the fix
        assert (
            run(
                """
                def process_delete(key, sleep):
                    sleep(0.1)
                    return key
                """
            )
            == []
        )

    def test_sleep_outside_reconcile_is_clean(self):
        assert run("import time\n\ndef wait_until(p):\n    time.sleep(0.1)\n") == []


# ---------------------------------------------------------------------------
# reconcile-returns-result
# ---------------------------------------------------------------------------


class TestReconcileReturnsResult:
    def test_fall_through_fires_once(self):
        v = only(
            run(
                """
                def process_x(key) -> Result:
                    if key:
                        return Result()
                """
            ),
            "reconcile-returns-result",
        )
        assert "fall off the end" in v.message

    def test_bare_return_fires_once(self):
        v = only(
            run(
                """
                def process_x(key) -> Result:
                    if not key:
                        return
                    return Result()
                """
            ),
            "reconcile-returns-result",
        )
        assert "bare `return`" in v.message

    def test_all_paths_returning_is_clean(self):
        assert (
            run(
                """
                def process_x(key) -> Result:
                    try:
                        if key:
                            return Result(requeue=True)
                        return Result()
                    except ValueError:
                        raise
                """
            )
            == []
        )

    def test_unannotated_helper_is_clean(self):
        assert run("def helper(key):\n    if key:\n        return 1\n") == []


# ---------------------------------------------------------------------------
# unguarded-optional-import
# ---------------------------------------------------------------------------


class TestUnguardedOptionalImport:
    def test_uninstalled_module_level_import_fires_once(self):
        v = only(
            run("import hypothesis\n", installed=frozenset({"pytest"})),
            "unguarded-optional-import",
        )
        assert "hypothesis" in v.message

    def test_ci_installed_import_is_clean(self):
        assert run("import yaml\nimport pytest\n") == []

    def test_guarded_imports_are_clean(self):
        assert (
            run(
                """
                try:
                    import hypothesis
                except ImportError:
                    hypothesis = None

                def lazy():
                    import hypothesis
                """,
                installed=frozenset(),
            )
            == []
        )

    def test_stdlib_and_first_party_are_clean(self):
        assert (
            run(
                "import threading\nfrom agac_tpu import klog\nfrom . import x\n",
                installed=frozenset(),
            )
            == []
        )


# ---------------------------------------------------------------------------
# suppression contract
# ---------------------------------------------------------------------------


class TestSuppression:
    SRC = "def f(self):\n    self._lock.acquire()  # agac-lint: ignore[bare-lock-acquire]{why}\n"

    def test_justified_suppression_silences_the_rule(self):
        assert run(self.SRC.format(why=" -- handoff: released by the waker thread")) == []

    def test_suppression_without_justification_is_itself_a_violation(self):
        v = only(run(self.SRC.format(why="")), "suppression-needs-justification")
        assert "justification" in v.message

    def test_suppression_for_a_different_rule_does_not_apply(self):
        src = "def f(self):\n    self._lock.acquire()  # agac-lint: ignore[blocking-reconcile] -- wrong rule\n"
        only(run(src), "bare-lock-acquire")


# ---------------------------------------------------------------------------
# drift-read-outside-read-plane
# ---------------------------------------------------------------------------


class TestDriftReadOutsideReadPlane:
    DRIVER = "agac_tpu/cloudprovider/aws/driver.py"

    def test_raw_read_in_ensure_path_fires_once(self):
        v = only(
            run(
                """
                class AWSDriver:
                    def _ensure_thing(self, arn):
                        return self.ga.list_listeners(arn, 100, None)
                """,
                path=self.DRIVER,
            ),
            "drift-read-outside-read-plane",
        )
        assert "ga.list_listeners" in v.message and "read plane" in v.message

    def test_raw_describe_on_route53_handle_fires(self):
        only(
            run(
                """
                class AWSDriver:
                    def _verify_records(self, zone_id):
                        return self.route53.list_resource_record_sets(zone_id, 300, None)
                """,
                path=self.DRIVER,
            ),
            "drift-read-outside-read-plane",
        )

    def test_sanctioned_loader_is_clean(self):
        assert (
            run(
                """
                class AWSDriver:
                    def _fetch_record_sets(self, zone_id):
                        return self.route53.list_resource_record_sets(zone_id, 300, None)

                    def _describe_load_balancers(self, names):
                        return self.elbv2.describe_load_balancers(names)
                """,
                path=self.DRIVER,
            )
            == []
        )

    def test_mutates_are_not_reads(self):
        assert (
            run(
                """
                class AWSDriver:
                    def _repair(self, arn):
                        self.ga.update_accelerator(arn, enabled=True)
                """,
                path=self.DRIVER,
            )
            == []
        )

    def test_rule_is_scoped_to_the_driver_module(self):
        # backends and tests list raw ops by design
        assert (
            run(
                "def probe(ga):\n    return ga.list_listeners('arn', 100, None)\n",
                path="tests/test_something.py",
            )
            == []
        )


# ---------------------------------------------------------------------------
# unbounded-poll-loop
# ---------------------------------------------------------------------------


class TestUnboundedPollLoop:
    def test_sleepy_poll_without_deadline_fires_once(self):
        # a describe+sleep settle loop is ALSO a blocking-settle
        # violation since ISSUE 6 — the two rules layer: this one
        # demands a deadline, the settle rule demands parking
        violations = run(
            """
            def wait_settled(self, arn):
                while True:
                    status = self.ga.describe_accelerator(arn).status
                    if status == "DEPLOYED":
                        return
                    self._sleep(self._poll_interval)
            """,
            path="agac_tpu/cloudprovider/aws/bad.py",
        )
        assert sorted(v.rule for v in violations) == [
            "blocking-settle-in-worker", "unbounded-poll-loop",
        ], violations
        v = next(v for v in violations if v.rule == "unbounded-poll-loop")
        assert "deadline" in v.message

    def test_deadline_consulting_loop_is_clean(self):
        # clean for THIS rule; the settle rule still demands parking —
        # a deadline bounds the wedge, it does not un-hold the worker
        violations = run(
            """
            def wait_settled(self, arn):
                deadline = monotonic() + self._poll_timeout
                while True:
                    if self.ga.describe_accelerator(arn).status == "DEPLOYED":
                        return
                    if monotonic() >= deadline:
                        raise TimeoutError(arn)
                    self._sleep(self._poll_interval)
            """,
            path="agac_tpu/cloudprovider/aws/good.py",
        )
        assert [v.rule for v in violations] == ["blocking-settle-in-worker"]

    def test_health_plane_consulting_loop_is_clean(self):
        violations = run(
            """
            def wait_settled(self, arn):
                while True:
                    if self.ga.describe_accelerator(arn).status == "DEPLOYED":
                        return
                    api_health.check_deadline("settle poll")
                    self._sleep(self._poll_interval)
            """,
            path="agac_tpu/cloudprovider/aws/good.py",
        )
        assert [v.rule for v in violations] == ["blocking-settle-in-worker"]

    def test_sleepless_loop_is_clean(self):
        # a tight computational loop is not a poll
        assert (
            run(
                """
                def drain(self, pages):
                    while pages:
                        pages.pop()
                """,
                path="agac_tpu/cloudprovider/aws/good.py",
            )
            == []
        )

    def test_rule_is_scoped_to_cloudprovider_and_controllers(self):
        # the workqueue's delay waker sleeps by design under its own
        # condition variable; the rule targets backend-facing polls
        assert (
            run(
                """
                def wait_settled(self, arn):
                    while True:
                        self._sleep(1.0)
                """,
                path="agac_tpu/reconcile/whatever.py",
            )
            == []
        )


# ---------------------------------------------------------------------------
# blocking-settle-in-worker
# ---------------------------------------------------------------------------


class TestBlockingSettleInWorker:
    def test_settle_loop_in_driver_fires_once(self):
        violations = run(
            """
            def _wait_for_deployed(self, arn):
                while True:
                    accelerator = self.ga.describe_accelerator(arn)
                    if accelerator.status == "DEPLOYED":
                        return
                    api_health.check_deadline("settle")
                    self._sleep(self._poll_interval)
            """,
            path="agac_tpu/cloudprovider/aws/bad.py",
        )
        # deadline-consulting, so unbounded-poll-loop stays quiet — the
        # settle rule is the only one that fires: bounded or not, the
        # loop HOLDS a worker that should have parked
        v = only(violations, "blocking-settle-in-worker")
        assert "SettleWait" in v.message

    def test_settle_loop_in_controller_fires(self):
        violations = run(
            """
            def process_thing(self, cloud, arn) -> "Result":
                while cloud.ga.list_listeners(arn, 100, None):
                    time.sleep(0.5)
                return Result()
            """,
            path="agac_tpu/controllers/bad.py",
        )
        assert "blocking-settle-in-worker" in {v.rule for v in violations}

    def test_pending_settle_scheduler_is_sanctioned(self):
        # the poll-tick scheduler re-checks parked chains between
        # sleeps BY DESIGN — reconcile/pending.py is the one home
        assert (
            run(
                """
                def loop(self):
                    while not self._stop.wait(self.interval):
                        ready = self._poller.list_accelerators(100, None)
                        self._sleep(0.0)
                """,
                path="agac_tpu/reconcile/pending.py",
            )
            == []
        )

    def test_sleep_only_retry_loop_is_clean(self):
        # sleeping without re-reading remote state is not a settle
        # poll (bounding such loops is unbounded-poll-loop's business)
        violations = run(
            """
            def retry(self):
                while self._tries < 3:
                    self._tries += 1
                    self._sleep(0.1)
            """,
            path="agac_tpu/cloudprovider/aws/good.py",
        )
        assert "blocking-settle-in-worker" not in {v.rule for v in violations}

    def test_read_only_drain_loop_is_clean(self):
        # paging drains re-read without sleeping — not a settle poll
        assert (
            run(
                """
                def drain(self):
                    token = None
                    while True:
                        page, token = self.ga.list_accelerators(100, token)
                        if token is None:
                            return page
                """,
                path="agac_tpu/cloudprovider/aws/good.py",
            )
            == []
        )

    def test_suppressed_parity_fallback_needs_justification(self):
        src = """
        def _blocking_settle_poll(self, arn):
            while True:  # agac-lint: ignore[blocking-settle-in-worker]
                if self.ga.describe_accelerator(arn).status == "DEPLOYED":
                    return
                api_health.check_deadline("settle")
                self._sleep(1.0)
        """
        violations = run(src, path="agac_tpu/cloudprovider/aws/bad.py")
        assert {v.rule for v in violations} == {"suppression-needs-justification"}


# ---------------------------------------------------------------------------
# delete-without-ownership-check
# ---------------------------------------------------------------------------


class TestDeleteWithoutOwnershipCheck:
    GC = "agac_tpu/controllers/garbagecollector.py"

    def test_unverified_cleanup_fires_once(self):
        v = only(
            run(
                """
                class GarbageCollector:
                    def _sweep(self, cloud, arn):
                        cloud.cleanup_global_accelerator(arn)
                """,
                path=self.GC,
            ),
            "delete-without-ownership-check",
        )
        assert "ownership-verification" in v.message

    def test_unverified_record_delete_fires(self):
        only(
            run(
                """
                class GarbageCollector:
                    def _sweep(self, cloud, owner):
                        cloud.cleanup_record_set("c", *owner)
                """,
                path=self.GC,
            ),
            "delete-without-ownership-check",
        )

    def test_verified_funnel_is_clean(self):
        assert (
            run(
                """
                class GarbageCollector:
                    def _delete_orphan(self, cloud, arn, owner):
                        if not verify_accelerator_orphan_ownership(
                            cloud, arn, self._cluster, owner, self._owner_exists
                        ):
                            return False
                        cloud.cleanup_global_accelerator(arn)
                        return True
                """,
                path=self.GC,
            )
            == []
        )

    def test_verify_helper_itself_is_sanctioned(self):
        # the helper's own live pre-deletion reads/deletes are the
        # sanctioned site (it IS the verification)
        assert (
            run(
                """
                def verify_record_orphan_ownership(cloud, cluster, owner):
                    cloud.cleanup_record_set(cluster, *owner)
                """,
                path=self.GC,
            )
            == []
        )

    def test_rule_is_scoped_to_the_gc_module(self):
        # the reactive controllers' cleanups are owner-event-driven —
        # the rule targets the sweeper's self-initiated deletions
        assert (
            run(
                """
                def process_delete(self, cloud, arn):
                    cloud.cleanup_global_accelerator(arn)
                """,
                path="agac_tpu/controllers/globalaccelerator.py",
            )
            == []
        )


# ---------------------------------------------------------------------------
# unregistered-metric
# ---------------------------------------------------------------------------


class TestUnregisteredMetric:
    def test_direct_construction_fires_once(self):
        v = only(
            run(
                """
                from agac_tpu.observability.metrics import Counter

                calls = Counter("agac_calls_total", "help", "counter")
                """
            ),
            "unregistered-metric",
        )
        assert "bypasses the registry" in v.message

    def test_module_attribute_construction_fires(self):
        only(
            run(
                """
                from agac_tpu.observability import metrics

                depth = metrics.Gauge("agac_depth", "help", "gauge")
                """
            ),
            "unregistered-metric",
        )

    def test_relative_import_construction_fires(self):
        only(
            run(
                """
                from .metrics import Histogram

                lat = Histogram("agac_lat", "help", "histogram")
                """,
                path="agac_tpu/observability/instruments.py",
            ),
            "unregistered-metric",
        )

    def test_collections_counter_is_clean(self):
        # provenance-tracked: only the observability primitives count
        assert (
            run(
                """
                from collections import Counter

                tally = Counter()
                """
            )
            == []
        )

    def test_registry_factory_with_literals_is_clean(self):
        assert (
            run(
                """
                def build(registry):
                    return registry.counter(
                        "agac_sweeps_total", "sweeps", labels=("kind",)
                    )
                """
            )
            == []
        )

    def test_non_literal_metric_name_fires(self):
        v = only(
            run(
                """
                def build(registry, name):
                    return registry.counter(name, "help")
                """
            ),
            "unregistered-metric",
        )
        assert "non-literal metric name" in v.message

    def test_non_literal_label_names_fire(self):
        v = only(
            run(
                """
                def build(registry, label_set):
                    return registry.gauge("agac_depth", "help", labels=label_set)
                """
            ),
            "unregistered-metric",
        )
        assert "cardinality" in v.message

    def test_metrics_module_itself_is_exempt(self):
        # the registry module is where the primitives are constructed
        assert (
            run(
                """
                from agac_tpu.observability.metrics import Counter

                child = Counter("agac_x_total", "help", "counter")
                """,
                path="agac_tpu/observability/metrics.py",
            )
            == []
        )


# ---------------------------------------------------------------------------
# the repo itself + CI wiring
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# unseamed-clock
# ---------------------------------------------------------------------------


class TestUnseamedClock:
    def test_direct_sleep_fires_once(self):
        v = only(
            run(
                "import time\n\ndef run_loop(self):\n    time.sleep(1.0)\n",
                path="agac_tpu/manager.py",
            ),
            "unseamed-clock",
        )
        assert "time.sleep" in v.message and "clockseam" in v.message

    def test_direct_monotonic_read_fires_once(self):
        v = only(
            run(
                "import time\n\ndef age(self):\n    return time.monotonic() - self.t0\n",
                path="agac_tpu/reconcile/pending.py",
            ),
            "unseamed-clock",
        )
        assert "time.monotonic" in v.message

    def test_wall_clock_and_time_ns_fire(self):
        violations = run(
            """
            import time

            def stamp(self):
                return time.time(), time.time_ns()
            """,
            path="agac_tpu/cluster/record.py",
        )
        assert [v.rule for v in violations] == ["unseamed-clock"] * 2

    def test_threading_timer_fires_once(self):
        v = only(
            run(
                "import threading\n\ndef arm(self):\n    threading.Timer(5.0, self.tick).start()\n",
                path="agac_tpu/controllers/route53.py",
            ),
            "unseamed-clock",
        )
        assert "Timer" in v.message and "scheduler" in v.message

    def test_from_import_aliases_fire(self):
        violations = run(
            """
            from time import sleep as pause
            from threading import Timer

            def f(self):
                pause(0.1)
                Timer(1.0, f)
            """,
            path="agac_tpu/cluster/informer.py",
        )
        assert [v.rule for v in violations] == ["unseamed-clock"] * 2

    def test_seam_and_injected_clock_are_clean(self):
        assert (
            run(
                """
                from .. import clockseam

                def loop(self, clock=None):
                    self._clock = clock or clockseam.monotonic
                    clockseam.sleep(0.5)
                    return self._clock()
                """,
                path="agac_tpu/cloudprovider/aws/health.py",
            )
            == []
        )

    def test_formatting_helpers_are_clean(self):
        # strftime/gmtime render a timestamp they are handed — only
        # clock READS and sleeps are seam business
        assert (
            run(
                "import time\n\ndef iso(now):\n    return time.strftime('%Y', time.gmtime(now))\n",
                path="agac_tpu/cluster/record.py",
            )
            == []
        )

    def test_real_io_edges_are_sanctioned(self):
        # SigV4 signing and real-HTTP token expiry NEED the real wall
        # clock; virtual time there would sign unusable requests
        for path in (
            "agac_tpu/cloudprovider/aws/sigv4.py",
            "agac_tpu/cloudprovider/aws/real_backend.py",
            "agac_tpu/cluster/rest.py",
            "agac_tpu/cluster/testserver.py",
            "agac_tpu/sim/runtime.py",
            "agac_tpu/clockseam.py",
        ):
            assert (
                run("import time\n\ndef f():\n    return time.time()\n", path=path)
                == []
            ), path

    def test_tests_and_bench_are_out_of_scope(self):
        # wall-clock tiers drive real threads on purpose
        assert (
            run(
                "import time\n\ndef wait_until(p):\n    time.sleep(0.02)\n",
                path="tests/test_resilience_e2e.py",
            )
            == []
        )

    def test_suppression_with_justification_holds(self):
        assert (
            run(
                "import time\n\ndef pop(self):\n"
                "    deadline = time.monotonic() + 1.0  # agac-lint: ignore[unseamed-clock] -- bounds a real blocked thread\n",
                path="agac_tpu/reconcile/workqueue.py",
            )
            == []
        )


class TestCrossShardSweep:
    """The sharding plane's enumeration-path gate (ISSUE 8): a GC
    sweep phase or drift enumeration that forgets the shard filter
    silently makes every replica work every key."""

    def test_unfiltered_sweep_phase_fires_once(self):
        v = only(
            run(
                """
                class GarbageCollector:
                    def _sweep_accelerators(self, cloud, report, budget):
                        for accelerator, tags in cloud.list_cluster_owned_pairs("c"):
                            report["candidates"] += 1
                """,
                path="agac_tpu/controllers/garbagecollector.py",
            ),
            "cross-shard-sweep",
        )
        assert "_sweep_accelerators" in v.message

    def test_filtered_sweep_phase_is_clean(self):
        assert (
            run(
                """
                class GarbageCollector:
                    def _sweep_accelerators(self, cloud, report, budget):
                        for accelerator, owner in cloud.list_cluster_owned_pairs("c"):
                            if not self._shards.owns(owner[1], owner[2]):
                                continue
                            report["candidates"] += 1
                """,
                path="agac_tpu/controllers/garbagecollector.py",
            )
            == []
        )

    def test_unfiltered_drift_sources_fire(self):
        v = only(
            run(
                """
                class Controller:
                    def drift_resync_sources(self):
                        return [(self.lister, lambda o: True, self.queue.add)]
                """,
                path="agac_tpu/controllers/somecontroller.py",
            ),
            "cross-shard-sweep",
        )
        assert "drift_resync_sources" in v.message

    def test_shard_aware_drift_sources_are_clean(self):
        assert (
            run(
                """
                class Controller:
                    def drift_resync_sources(self):
                        owns = self._shards.owns_obj
                        return [(self.lister, owns, self.queue.add)]
                """,
                path="agac_tpu/controllers/somecontroller.py",
            )
            == []
        )

    def test_unfiltered_manager_drift_tick_fires(self):
        v = only(
            run(
                """
                class Manager:
                    def drift_tick(self):
                        for name, controller in self.controllers.items():
                            for lister, predicate, enqueue in controller.drift_resync_sources():
                                for obj in lister.list():
                                    enqueue(obj)
                """,
                path="agac_tpu/manager.py",
            ),
            "cross-shard-sweep",
        )
        assert "drift_tick" in v.message

    def test_rule_is_scoped_to_manager_and_controllers(self):
        # the same unfiltered shape outside the enumeration modules
        # (e.g. a driver helper) is out of scope
        assert (
            run(
                """
                def drift_tick(self):
                    for obj in self.lister.list():
                        self.enqueue(obj)
                """,
                path="agac_tpu/cloudprovider/aws/driver.py",
            )
            == []
        )

    def test_suppression_needs_justification(self):
        src = """
        class Manager:
            def drift_tick(self):  # agac-lint: ignore[cross-shard-sweep] -- single-process tick by design
                for obj in self.lister.list():
                    self.enqueue(obj)
        """
        assert run(src, path="agac_tpu/manager.py") == []
        bare = src.replace(" -- single-process tick by design", "")
        violations = run(bare, path="agac_tpu/manager.py")
        assert violations, "suppression without justification must not hold"


class TestJourneyStageWithoutStamp:
    """The convergence SLO plane's stamp gate (ISSUE 9): a reconcile
    path that requeues/parks/drops without a journey stamp is latency
    the /slo drill-down can never explain."""

    def test_unstamped_requeue_fires_once(self):
        v = only(
            run(
                """
                def _handle(key, queue):
                    queue.add_rate_limited(key, reason="backoff")
                """,
                path="agac_tpu/reconcile/loop.py",
            ),
            "journey-stage-without-stamp",
        )
        assert "add_rate_limited" in v.message and "journey" in v.message

    def test_unstamped_park_fires_once(self):
        v = only(
            run(
                """
                def _handle(key, queue, table, wait):
                    table.park(key, queue, wait, reason="parked-settle")
                """,
                path="agac_tpu/reconcile/loop.py",
            ),
            "journey-stage-without-stamp",
        )
        assert "park" in v.message

    def test_stamped_requeue_is_clean(self):
        assert (
            run(
                """
                from ..observability import journey

                def _handle(key, queue):
                    journey.tracker().stage("ctrl", key, "requeued")
                    queue.add_rate_limited(key, reason="backoff")
                """,
                path="agac_tpu/reconcile/loop.py",
            )
            == []
        )

    def test_journey_close_counts_as_a_stamp(self):
        assert (
            run(
                """
                def _expire(entry, journeys):
                    journeys.drop("ctrl", entry.key)
                    entry.queue.add_after(entry.key, 5.0, reason="backoff")
                """,
                path="agac_tpu/reconcile/pending_extra.py",
            )
            == []
        )

    def test_workqueue_mechanism_is_exempt(self):
        # the queue implementation's internal re-adds are mechanism,
        # not lifecycle decisions
        assert (
            run(
                """
                def requeue_internal(self, item):
                    self.add_rate_limited(item)
                """,
                path="agac_tpu/reconcile/workqueue.py",
            )
            == []
        )

    def test_rule_is_scoped_to_the_reconcile_package(self):
        # controllers' enqueue paths carry their own stamps; the rule
        # polices the loop package where the retry policy lives
        assert (
            run(
                """
                def _enqueue(self, queue, obj):
                    queue.add_rate_limited(key(obj), reason="backoff")
                """,
                path="agac_tpu/controllers/somecontroller.py",
            )
            == []
        )

    def test_suppression_needs_justification(self):
        src = """
        def _handle(key, queue):
            queue.add_rate_limited(key, reason="backoff")  # agac-lint: ignore[journey-stage-without-stamp] -- test-only shim queue
        """
        assert run(src, path="agac_tpu/reconcile/loop.py") == []
        bare = src.replace(" -- test-only shim queue", "")
        assert run(bare, path="agac_tpu/reconcile/loop.py"), (
            "suppression without justification must not hold"
        )


# ---------------------------------------------------------------------------
# unexplained-requeue
# ---------------------------------------------------------------------------


class TestUnexplainedRequeue:
    """The explain plane's feed gate (ISSUE 15): every requeue, park,
    and fate-carrying Result at a reconcile/controller call site must
    state a reason code the explain catalog can classify."""

    def test_missing_reason_fires_once(self):
        v = only(
            run(
                """
                def _handle(key, queue, journeys):
                    journeys.stage("ctrl", key, "requeued")
                    queue.add_rate_limited(key)
                """,
                path="agac_tpu/reconcile/loop.py",
            ),
            "unexplained-requeue",
        )
        assert "add_rate_limited" in v.message and "reason" in v.message

    def test_computed_reason_fires_once(self):
        v = only(
            run(
                """
                def _handle(key, queue, journeys, why):
                    journeys.stage("ctrl", key, "requeued")
                    queue.add_after(key, 5.0, reason="re-" + why)
                """,
                path="agac_tpu/reconcile/loop.py",
            ),
            "unexplained-requeue",
        )
        assert "literal" in v.message

    def test_uncataloged_literal_fires_once(self):
        v = only(
            run(
                """
                def _handle(key, queue, journeys):
                    journeys.stage("ctrl", key, "requeued")
                    queue.add_rate_limited(key, reason="because-reasons")
                """,
                path="agac_tpu/reconcile/loop.py",
            ),
            "unexplained-requeue",
        )
        assert "because-reasons" in v.message

    def test_cataloged_literal_is_clean(self):
        assert (
            run(
                """
                def _handle(key, queue, journeys):
                    journeys.stage("ctrl", key, "requeued")
                    queue.add_rate_limited(key, reason="circuit-open")
                """,
                path="agac_tpu/reconcile/loop.py",
            )
            == []
        )

    def test_result_reason_passthrough_is_clean(self):
        # the reconcile loop relays the controller's own verdict:
        # res.reason is attribute provenance, not a new decision
        assert (
            run(
                """
                def _handle(key, queue, journeys, res):
                    journeys.stage("ctrl", key, "requeued")
                    queue.add_rate_limited(key, reason=res.reason)
                """,
                path="agac_tpu/reconcile/loop.py",
            )
            == []
        )

    def test_result_fate_without_reason_fires_once(self):
        v = only(
            run(
                """
                def reconcile_widget(obj) -> "Result":
                    return Result(requeue_after=30.0)
                """,
                path="agac_tpu/controllers/widget.py",
            ),
            "unexplained-requeue",
        )
        assert "Result" in v.message
        assert (
            run(
                """
                def reconcile_widget(obj) -> "Result":
                    return Result(requeue_after=30.0, reason="in-flight")
                """,
                path="agac_tpu/controllers/widget.py",
            )
            == []
        )

    def test_workqueue_mechanism_and_other_packages_are_exempt(self):
        src = """
        def requeue_internal(self, item):
            self.add_rate_limited(item)
        """
        assert run(src, path="agac_tpu/reconcile/workqueue.py") == []
        assert run(src, path="agac_tpu/observability/journey.py") == []

    def test_suppression_needs_justification(self):
        src = """
        def _handle(key, queue, journeys):
            journeys.stage("ctrl", key, "requeued")
            queue.add_rate_limited(key)  # agac-lint: ignore[unexplained-requeue] -- reason attached upstream by shim
        """
        assert run(src, path="agac_tpu/reconcile/loop.py") == []
        bare = src.replace(" -- reason attached upstream by shim", "")
        assert run(bare, path="agac_tpu/reconcile/loop.py"), (
            "suppression without justification must not hold"
        )

    def test_reason_catalog_matches_the_explain_plane(self):
        # the rule's literal copy (the linter never imports the linted
        # package) must track the explain catalog exactly
        from agac_tpu.analysis.rules import _REQUEUE_REASON_CODES
        from agac_tpu.observability import explain

        assert _REQUEUE_REASON_CODES == explain.REASON_CODES


# ---------------------------------------------------------------------------
# unattributed-stage
# ---------------------------------------------------------------------------


class TestUnattributedStage:
    def test_uncataloged_stage_name_fires_once(self):
        v = only(
            run(
                """
                from agac_tpu.observability import profile

                def tick():
                    with profile.stage("my-new-hotpath"):
                        pass
                """,
                path="agac_tpu/controllers/bad.py",
            ),
            "unattributed-stage",
        )
        assert "my-new-hotpath" in v.message and "STAGES" in v.message

    def test_computed_stage_name_fires_once(self):
        v = only(
            run(
                """
                from agac_tpu.observability import profile

                def tick(name):
                    with profile.stage(f"dyn-{name}"):
                        pass
                """,
                path="agac_tpu/manager.py",
            ),
            "unattributed-stage",
        )
        assert "computed" in v.message and "api_stage" in v.message

    def test_cataloged_literal_is_clean(self):
        assert (
            run(
                """
                from agac_tpu.observability import profile as obs_profile

                def tick():
                    with obs_profile.stage("drift-tick"):
                        pass
                """,
                path="agac_tpu/manager.py",
            )
            == []
        )

    def test_api_stage_carries_the_dynamic_family(self):
        # per-AWS-op names are namespaced by api_stage on purpose; the
        # rule must not flag the sanctioned dynamic path
        assert (
            run(
                """
                from agac_tpu.observability import profile

                def observed(service, op):
                    with profile.api_stage(service, op):
                        pass
                """,
                path="agac_tpu/observability/instruments.py",
            )
            == []
        )

    def test_unrelated_stage_functions_stay_out_of_scope(self):
        # provenance keeps e.g. a theatrical `stage()` helper unflagged
        assert (
            run(
                """
                from agac_tpu.sim.theatre import stage

                def play():
                    with stage("curtain-up"):
                        pass
                """,
                path="agac_tpu/controllers/good.py",
            )
            == []
        )

    def test_stage_catalog_matches_the_accountant(self):
        # the rule's literal copy (the linter never imports the linted
        # package) must track the accountant's catalog exactly
        from agac_tpu.analysis.rules import _STAGE_NAMES
        from agac_tpu.observability import profile

        assert _STAGE_NAMES == frozenset(profile.STAGES)


# ---------------------------------------------------------------------------
# cross-boundary-capture
# ---------------------------------------------------------------------------


class TestCrossBoundaryCapture:
    def test_lambda_submission_fires_once(self):
        v = only(
            run(
                """
                def fan_out(pool, items):
                    return [pool.submit(lambda: item) for item in items]
                """,
                path="agac_tpu/cloudprovider/aws/bad.py",
            ),
            "cross-boundary-capture",
        )
        assert "lambda" in v.message and "pool.submit" in v.message

    def test_bound_method_submission_fires_once(self):
        v = only(
            run(
                """
                class Batcher:
                    def kick(self, executor):
                        return executor.submit(self.flush)

                    def flush(self):
                        return None
                """,
                path="agac_tpu/cloudprovider/aws/bad.py",
            ),
            "cross-boundary-capture",
        )
        assert "self.flush" in v.message

    def test_nested_def_with_captures_fires_once(self):
        v = only(
            run(
                """
                def fan_out(pool, items):
                    def work():
                        return items
                    return pool.submit(work)
                """,
                path="agac_tpu/cloudprovider/aws/bad.py",
            ),
            "cross-boundary-capture",
        )
        assert "'items'" in v.message

    def test_capture_free_nested_def_is_clean(self):
        # binds everything it loads: nothing to pickle by reference
        assert (
            run(
                """
                def fan_out(pool):
                    def work():
                        out = 1
                        return out
                    return pool.submit(work)
                """,
                path="agac_tpu/cloudprovider/aws/good.py",
            )
            == []
        )

    def test_module_level_function_is_clean(self):
        assert (
            run(
                """
                def work(item):
                    return item


                def fan_out(pool, items):
                    return pool.map(work, items)
                """,
                path="agac_tpu/cloudprovider/aws/good.py",
            )
            == []
        )

    def test_thread_target_lambda_fires_once(self):
        v = only(
            run(
                """
                import threading


                def kick():
                    threading.Thread(target=lambda: None).start()
                """,
                path="agac_tpu/cluster/bad.py",
            ),
            "cross-boundary-capture",
        )
        assert "Thread(target=...)" in v.message

    def test_thread_target_named_function_is_other_rules_jurisdiction(self):
        # nested-def / bound-method thread targets belong to the
        # unseamed-thread whole-program analysis, not this rule
        assert (
            run(
                """
                import threading


                def kick(run):
                    threading.Thread(target=run).start()
                """,
                path="agac_tpu/cluster/good.py",
            )
            == []
        )

    def test_non_poolish_receiver_is_clean(self):
        assert (
            run(
                """
                def render(canvas, items):
                    return canvas.map(lambda i: i, items)
                """,
                path="agac_tpu/controllers/good.py",
            )
            == []
        )

    def test_suppression_with_justification(self):
        src = """
            def fan_out(pool, items):
                return pool.submit(lambda: items)  # agac-lint: ignore[cross-boundary-capture] -- in-process pool behind the seam
        """
        assert run(src, path="agac_tpu/cloudprovider/aws/bad.py") == []

    def test_suppression_without_justification_is_rejected(self):
        src = """
            def fan_out(pool, items):
                return pool.submit(lambda: items)  # agac-lint: ignore[cross-boundary-capture]
        """
        violations = run(src, path="agac_tpu/cloudprovider/aws/bad.py")
        assert violations, "bare suppression must not silence the rule"

    def test_analysis_and_sim_are_exempt(self):
        src = """
            def fan_out(pool, items):
                return [pool.submit(lambda: item) for item in items]
        """
        assert run(src, path="agac_tpu/analysis/tooling.py") == []
        assert run(src, path="agac_tpu/sim/executor.py") == []


# ---------------------------------------------------------------------------
# untapped-external-input
# ---------------------------------------------------------------------------


class TestUntappedExternalInput:
    def test_untapped_informer_delivery_fires_once(self):
        v = only(
            run(
                """
                def pump(self, informer, events):
                    for event in events:
                        informer.apply_event(event)
                """,
                path="agac_tpu/sim/pump.py",
            ),
            "untapped-external-input",
        )
        assert "informer event delivery" in v.message

    def test_tapped_informer_delivery_is_clean(self):
        src = """
            def pump(self, informer, events, tap):
                for event in events:
                    informer.apply_event(event)
                if tap is not None:
                    tap.record_informer_batch(self.identity, informer.kind, events)
        """
        assert run(src, path="agac_tpu/sim/pump.py") == []

    def test_untapped_outcome_classification_fires_once(self):
        v = only(
            run(
                """
                def observed(trace, service, op, start, end, outcome):
                    trace.record_call(service, op, start, end, outcome)
                """,
                path="agac_tpu/observability/wrapping.py",
            ),
            "untapped-external-input",
        )
        assert "outcome classification" in v.message

    def test_tapped_outcome_classification_is_clean(self):
        src = """
            def observed(trace, tap, service, op, start, end, outcome):
                trace.record_call(service, op, start, end, outcome)
                if tap is not None:
                    tap.record_aws_call(service, op, outcome, None, None)
        """
        assert run(src, path="agac_tpu/observability/wrapping.py") == []

    def test_untapped_signal_registration_fires(self):
        v = only(
            run(
                """
                import signal

                def install(stop):
                    def handler(signum, frame):
                        stop.set()
                    signal.signal(signal.SIGTERM, handler)
                """,
                path="agac_tpu/shutdown.py",
            ),
            "untapped-external-input",
        )
        assert "signal handler registration" in v.message

    def test_nested_handler_feeding_the_tap_discharges(self):
        src = """
            import signal

            def install(stop):
                def handler(signum, frame):
                    from .sim.capture import active
                    tap = active()
                    if tap is not None:
                        tap.record_signal(signum)
                    stop.set()
                signal.signal(signal.SIGTERM, handler)
        """
        assert run(src, path="agac_tpu/shutdown.py") == []

    def test_capture_plane_itself_is_exempt(self):
        src = """
            def pump(self, informer, events):
                for event in events:
                    informer.apply_event(event)
        """
        assert run(src, path="agac_tpu/sim/capture.py") == []
        assert run(src, path="agac_tpu/sim/replay.py") == []

    def test_suppression_with_justification_is_honored(self):
        src = """
            def pump(self, informer, events):
                for event in events:
                    informer.apply_event(event)  # agac-lint: ignore[untapped-external-input] -- bench-only pump, never captured
        """
        assert run(src, path="agac_tpu/bench_support.py") == []


def test_rule_registry_ships_the_documented_rules():
    ids = {r.id for r in RULES}
    assert ids == {
        "raw-backend-call",
        "cross-boundary-capture",
        "bare-lock-acquire",
        "blocking-reconcile",
        "reconcile-returns-result",
        "unguarded-optional-import",
        "drift-read-outside-read-plane",
        "unbounded-poll-loop",
        "blocking-settle-in-worker",
        "delete-without-ownership-check",
        "unregistered-metric",
        "unseamed-clock",
        "cross-shard-sweep",
        "journey-stage-without-stamp",
        "unattributed-stage",
        "unexplained-requeue",
        "untapped-external-input",
    }


def test_parse_ci_installed_reads_workflow_pip_lines():
    installed = parse_ci_installed(REPO / ".github" / "workflows")
    # pyyaml maps to its import name; hypothesis is the ADVICE r5 #1 fix
    assert {"yaml", "pytest", "hypothesis"} <= installed


def test_repo_is_invariant_clean():
    """The acceptance bar: `make lint-invariants` (same targets, same
    rules) exits clean on this repo."""
    violations = lint_paths([REPO / "agac_tpu", REPO / "tests", REPO / "bench.py"])
    assert violations == [], "\n".join(v.render() for v in violations)
