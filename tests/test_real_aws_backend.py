"""Real AWS backend tests with stub transports: SigV4 against the
official AWS test vector, JSON 1.1 / Query-XML / REST-XML request
construction and response parsing, and error-code mapping."""

import datetime
import json
import urllib.parse

import pytest

from agac_tpu.cloudprovider.aws.errors import (
    AWSAPIError,
    EndpointGroupNotFoundException,
    ListenerNotFoundException,
)
from agac_tpu.cloudprovider.aws.real_backend import (
    RealELBv2API,
    RealGlobalAcceleratorAPI,
    RealRoute53API,
)
from agac_tpu.cloudprovider.aws.sigv4 import Credentials, sign_request
from agac_tpu.cloudprovider.aws.types import (
    AliasTarget,
    Change,
    EndpointConfiguration,
    PortRange,
    ResourceRecord,
    ResourceRecordSet,
    Tag,
)

CREDS = Credentials("AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY")


class StubTransport:
    def __init__(self):
        self.requests = []
        self.responses = []

    def queue(self, status, body):
        self.responses.append(
            (status, body if isinstance(body, bytes) else json.dumps(body).encode())
        )

    def __call__(self, method, url, headers, body, timeout):
        self.requests.append((method, url, headers, body))
        return self.responses.pop(0)


def test_sigv4_official_get_vanilla_vector():
    """AWS's published 'get-vanilla' SigV4 test case."""
    now = datetime.datetime(2015, 8, 30, 12, 36, 0, tzinfo=datetime.timezone.utc)
    signed = sign_request(
        "GET",
        "https://example.amazonaws.com/",
        {},
        b"",
        "service",
        "us-east-1",
        CREDS,
        now=now,
    )
    assert signed["Authorization"] == (
        "AWS4-HMAC-SHA256 "
        "Credential=AKIDEXAMPLE/20150830/us-east-1/service/aws4_request, "
        "SignedHeaders=host;x-amz-date, "
        "Signature=5fa00fa31553b73ebf1942676e86291e8372ff2a2260956d9b8aae1d763fbf31"
    )


def test_sigv4_query_ordering_vector():
    """AWS's 'get-vanilla-query-order-key-case' test case."""
    now = datetime.datetime(2015, 8, 30, 12, 36, 0, tzinfo=datetime.timezone.utc)
    signed = sign_request(
        "GET",
        "https://example.amazonaws.com/?Param2=value2&Param1=value1",
        {},
        b"",
        "service",
        "us-east-1",
        CREDS,
        now=now,
    )
    assert signed["Authorization"].endswith(
        "Signature=b97d918cfa904a5beff61c982a1b6f458b799221646efd99d3219ec94cdf2500"
    )


def test_session_token_header_included():
    creds = Credentials("AKID", "secret", session_token="tok123")
    signed = sign_request(
        "GET", "https://example.amazonaws.com/", {}, b"", "service", "us-east-1", creds
    )
    assert signed["X-Amz-Security-Token"] == "tok123"
    assert "x-amz-security-token" in signed["Authorization"]


class TestGlobalAcceleratorProtocol:
    @pytest.fixture
    def api(self):
        stub = StubTransport()
        return RealGlobalAcceleratorAPI(credentials=CREDS, transport=stub), stub

    def test_list_accelerators_request_and_parse(self, api):
        client, stub = api
        stub.queue(
            200,
            {
                "Accelerators": [
                    {
                        "AcceleratorArn": "arn:ga:1",
                        "Name": "web",
                        "DnsName": "abc.awsglobalaccelerator.com",
                        "Enabled": True,
                        "Status": "DEPLOYED",
                    }
                ],
                "NextToken": "tok",
            },
        )
        accelerators, token = client.list_accelerators(100, None)
        method, url, headers, body = stub.requests[0]
        assert method == "POST"
        assert url == "https://globalaccelerator.us-west-2.amazonaws.com/"
        assert headers["X-Amz-Target"] == "GlobalAccelerator_V20180706.ListAccelerators"
        assert headers["Content-Type"] == "application/x-amz-json-1.1"
        assert "Authorization" in headers
        assert json.loads(body) == {"MaxResults": 100}
        assert token == "tok"
        assert accelerators[0].accelerator_arn == "arn:ga:1"
        assert accelerators[0].status == "DEPLOYED"

    def test_create_accelerator_payload(self, api):
        client, stub = api
        stub.queue(200, {"Accelerator": {"AcceleratorArn": "arn:new"}})
        client.create_accelerator("name", "IPV4", True, [Tag("k", "v")])
        payload = json.loads(stub.requests[0][3])
        assert payload.pop("IdempotencyToken")
        assert payload == {
            "Name": "name",
            "IpAddressType": "IPV4",
            "Enabled": True,
            "Tags": [{"Key": "k", "Value": "v"}],
        }

    def test_create_listener_port_ranges(self, api):
        client, stub = api
        stub.queue(
            200,
            {
                "Listener": {
                    "ListenerArn": "arn:l",
                    "Protocol": "TCP",
                    "PortRanges": [{"FromPort": 80, "ToPort": 80}],
                }
            },
        )
        listener = client.create_listener("arn:ga", [PortRange(80, 80)], "TCP", "NONE")
        payload = json.loads(stub.requests[0][3])
        assert payload["PortRanges"] == [{"FromPort": 80, "ToPort": 80}]
        assert listener.port_ranges[0].from_port == 80

    def test_weight_zero_is_serialized(self, api):
        client, stub = api
        stub.queue(200, {"EndpointGroup": {"EndpointGroupArn": "arn:eg"}})
        client.update_endpoint_group(
            "arn:eg", [EndpointConfiguration(endpoint_id="arn:lb", weight=0)]
        )
        payload = json.loads(stub.requests[0][3])
        # weight 0 means "drain" in GA and must not be dropped
        assert payload["EndpointConfigurations"][0]["Weight"] == 0

    def test_error_code_mapping(self, api):
        client, stub = api
        stub.queue(
            400,
            {"__type": "com.amazon#EndpointGroupNotFoundException", "message": "gone"},
        )
        with pytest.raises(EndpointGroupNotFoundException):
            client.describe_endpoint_group("arn:eg")
        stub.queue(400, {"__type": "ListenerNotFoundException"})
        with pytest.raises(ListenerNotFoundException):
            client.list_listeners("arn:ga", 100, None)
        stub.queue(400, {"__type": "AccessDeniedException", "message": "no"})
        with pytest.raises(AWSAPIError) as exc:
            client.describe_accelerator("arn:a")
        assert exc.value.code == "AccessDeniedException"


class TestStandardRetryMode:
    """The SDK-level retry the reference inherits from aws-sdk-go-v2:
    throttles, 5xx and connection failures are retried with backoff
    before the error ever reaches the reconcile loop."""

    def make(self):
        stub = StubTransport()
        self.sleeps = []
        api = RealGlobalAcceleratorAPI(
            credentials=CREDS, transport=stub, sleep=self.sleeps.append
        )
        return api, stub

    def test_5xx_retried_until_success(self):
        client, stub = self.make()
        stub.queue(503, b"Service Unavailable")
        stub.queue(500, b"oops")
        stub.queue(200, {"Accelerators": []})
        accelerators, token = client.list_accelerators(100, None)
        assert accelerators == [] and token is None
        assert len(stub.requests) == 3
        # jittered exponential backoff between attempts
        assert len(self.sleeps) == 2 and all(s >= 0 for s in self.sleeps)

    def test_throttle_code_on_400_retried(self):
        client, stub = self.make()
        stub.queue(400, {"__type": "ThrottlingException", "message": "Rate exceeded"})
        stub.queue(200, {"Accelerators": []})
        accelerators, _ = client.list_accelerators(100, None)
        assert accelerators == []
        assert len(stub.requests) == 2

    def test_retries_exhausted_surfaces_last_error(self):
        client, stub = self.make()
        for _ in range(3):
            stub.queue(400, {"__type": "ThrottlingException", "message": "Rate exceeded"})
        with pytest.raises(AWSAPIError) as exc:
            client.list_accelerators(100, None)
        assert exc.value.code == "ThrottlingException"
        assert len(stub.requests) == 3

    def test_non_retryable_4xx_fails_immediately(self):
        client, stub = self.make()
        stub.queue(400, {"__type": "AccessDeniedException", "message": "no"})
        with pytest.raises(AWSAPIError) as exc:
            client.describe_accelerator("arn:a")
        assert exc.value.code == "AccessDeniedException"
        assert len(stub.requests) == 1

    def test_connection_errors_retried_then_raise(self):
        import urllib.error

        calls = []

        def flaky(method, url, headers, body, timeout):
            calls.append(url)
            if len(calls) < 3:
                raise urllib.error.URLError("connection refused")
            return 200, json.dumps({"Accelerators": []}).encode()

        client = RealGlobalAcceleratorAPI(
            credentials=CREDS, transport=flaky, sleep=lambda s: None
        )
        accelerators, _ = client.list_accelerators(100, None)
        assert accelerators == [] and len(calls) == 3

        calls.clear()

        def dead(method, url, headers, body, timeout):
            calls.append(url)
            raise urllib.error.URLError("connection refused")

        client = RealGlobalAcceleratorAPI(
            credentials=CREDS, transport=dead, sleep=lambda s: None
        )
        with pytest.raises(AWSAPIError) as exc:
            client.list_accelerators(100, None)
        assert exc.value.code == "RequestError"
        assert len(calls) == 3

    def test_message_echoing_throttle_word_not_retried(self):
        """Retryability is decided on the PARSED service code, never by
        substring-matching the body: a permanent validation error whose
        message merely mentions 'Throttling' fails immediately."""
        client, stub = self.make()
        stub.queue(
            400,
            {
                "__type": "ValidationException",
                "message": "tag value 'ThrottlingException-notes' is invalid",
            },
        )
        with pytest.raises(AWSAPIError) as exc:
            client.describe_accelerator("arn:a")
        assert exc.value.code == "ValidationException"
        assert len(stub.requests) == 1

    def test_create_calls_carry_idempotency_token(self):
        """Connection-error re-sends of the GA creates are
        duplicate-safe because every create carries an IdempotencyToken
        (the SDK auto-fills this field for the reference)."""
        client, stub = self.make()
        stub.queue(200, {"Accelerator": {"AcceleratorArn": "arn:a"}})
        stub.queue(200, {"Listener": {"ListenerArn": "arn:l"}})
        stub.queue(200, {"EndpointGroup": {"EndpointGroupArn": "arn:eg"}})
        client.create_accelerator("n", "IPV4", True, [])
        client.create_listener("arn:a", [PortRange(80, 80)], "TCP", "NONE")
        client.create_endpoint_group("arn:l", "us-west-2", [])
        tokens = [
            json.loads(body)["IdempotencyToken"] for _, _, _, body in stub.requests
        ]
        assert all(tokens) and len(set(tokens)) == 3

    def test_each_attempt_is_resigned(self):
        client, stub = self.make()
        stub.queue(503, b"")
        stub.queue(200, {"Accelerators": []})
        client.list_accelerators(100, None)
        auth = [headers["Authorization"] for _, _, headers, _ in stub.requests]
        assert len(auth) == 2 and all(a.startswith("AWS4-HMAC-SHA256") for a in auth)


class TestELBv2Protocol:
    def test_describe_load_balancers(self):
        stub = StubTransport()
        api = RealELBv2API("eu-west-1", credentials=CREDS, transport=stub)
        stub.queue(
            200,
            b"""<?xml version="1.0"?>
<DescribeLoadBalancersResponse xmlns="http://elasticloadbalancing.amazonaws.com/doc/2015-12-01/">
  <DescribeLoadBalancersResult>
    <LoadBalancers>
      <member>
        <LoadBalancerArn>arn:aws:elasticloadbalancing:eu-west-1:1:loadbalancer/net/web/1</LoadBalancerArn>
        <LoadBalancerName>web</LoadBalancerName>
        <DNSName>web-1.elb.eu-west-1.amazonaws.com</DNSName>
        <State><Code>active</Code></State>
        <Type>network</Type>
        <Scheme>internet-facing</Scheme>
      </member>
    </LoadBalancers>
  </DescribeLoadBalancersResult>
</DescribeLoadBalancersResponse>""",
        )
        lbs = api.describe_load_balancers(["web"])
        method, url, headers, body = stub.requests[0]
        assert url == "https://elasticloadbalancing.eu-west-1.amazonaws.com/"
        params = dict(urllib.parse.parse_qsl(body.decode()))
        assert params["Action"] == "DescribeLoadBalancers"
        assert params["Names.member.1"] == "web"
        assert lbs[0].load_balancer_name == "web"
        assert lbs[0].state_code == "active"

    def test_xml_error_mapping(self):
        stub = StubTransport()
        api = RealELBv2API("eu-west-1", credentials=CREDS, transport=stub)
        stub.queue(
            400,
            b"""<ErrorResponse xmlns="http://elasticloadbalancing.amazonaws.com/doc/2015-12-01/">
  <Error><Type>Sender</Type><Code>LoadBalancerNotFound</Code><Message>nope</Message></Error>
</ErrorResponse>""",
        )
        with pytest.raises(AWSAPIError) as exc:
            api.describe_load_balancers(["missing"])
        assert exc.value.code == "LoadBalancerNotFound"


class TestRoute53Protocol:
    @pytest.fixture
    def api(self):
        stub = StubTransport()
        return RealRoute53API(credentials=CREDS, transport=stub), stub

    def test_list_hosted_zones_by_name(self, api):
        client, stub = api
        stub.queue(
            200,
            b"""<ListHostedZonesByNameResponse xmlns="https://route53.amazonaws.com/doc/2013-04-01/">
  <HostedZones><HostedZone><Id>/hostedzone/Z1</Id><Name>example.com.</Name></HostedZone></HostedZones>
</ListHostedZonesByNameResponse>""",
        )
        zones = client.list_hosted_zones_by_name("example.com.", 1)
        url = stub.requests[0][1]
        assert "/2013-04-01/hostedzonesbyname?" in url
        assert "dnsname=example.com." in url
        assert zones[0].id == "/hostedzone/Z1"

    def test_change_batch_xml(self, api):
        client, stub = api
        stub.queue(200, b"<ChangeResourceRecordSetsResponse/>")
        client.change_resource_record_sets(
            "/hostedzone/Z1",
            [
                Change(
                    "CREATE",
                    ResourceRecordSet(
                        name="app.example.com",
                        type="A",
                        alias_target=AliasTarget(
                            dns_name="abc.awsglobalaccelerator.com",
                            evaluate_target_health=True,
                            hosted_zone_id="Z2BJ6XQ5FK7U4H",
                        ),
                    ),
                ),
                Change(
                    "CREATE",
                    ResourceRecordSet(
                        name="app.example.com",
                        type="TXT",
                        ttl=300,
                        resource_records=[ResourceRecord('"heritage=..."')],
                    ),
                ),
            ],
        )
        method, url, headers, body = stub.requests[0]
        assert method == "POST"
        assert url.endswith("/2013-04-01/hostedzone/Z1/rrset")
        text = body.decode()
        assert "<Action>CREATE</Action>" in text
        assert "<HostedZoneId>Z2BJ6XQ5FK7U4H</HostedZoneId>" in text
        assert "<TTL>300</TTL>" in text
        assert '<Value>"heritage=..."</Value>' in text

    def test_list_record_sets_pagination_flag(self, api):
        client, stub = api
        stub.queue(
            200,
            b"""<ListResourceRecordSetsResponse xmlns="https://route53.amazonaws.com/doc/2013-04-01/">
  <ResourceRecordSets>
    <ResourceRecordSet><Name>a.example.com.</Name><Type>A</Type>
      <AliasTarget><HostedZoneId>Z2BJ6XQ5FK7U4H</HostedZoneId><DNSName>x.com.</DNSName><EvaluateTargetHealth>true</EvaluateTargetHealth></AliasTarget>
    </ResourceRecordSet>
  </ResourceRecordSets>
  <IsTruncated>true</IsTruncated>
  <NextRecordName>b.example.com.</NextRecordName>
</ListResourceRecordSetsResponse>""",
        )
        records, next_name = client.list_resource_record_sets("/hostedzone/Z1", 300, None)
        assert next_name == "b.example.com."
        assert records[0].alias_target.dns_name == "x.com."
        assert records[0].alias_target.evaluate_target_health is True

    def test_route53_error(self, api):
        client, stub = api
        stub.queue(
            404,
            b"""<ErrorResponse xmlns="https://route53.amazonaws.com/doc/2013-04-01/">
  <Error><Code>NoSuchHostedZone</Code><Message>gone</Message></Error>
</ErrorResponse>""",
        )
        with pytest.raises(AWSAPIError) as exc:
            client.list_hosted_zones(100, None)
        assert exc.value.code == "NoSuchHostedZone"


def test_from_environment_shares_one_credential_provider(monkeypatch):
    """`from_environment` runs per reconcile; every bundle must reuse
    the process-wide provider so IRSA resolution (an STS round trip)
    happens once per expiry window, not once per work item."""
    from agac_tpu.cloudprovider.aws import real_backend

    monkeypatch.setattr(real_backend, "_process_provider", None)
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKID")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SECRET")
    a = real_backend.RealAWSClients.from_environment("us-west-2")
    b = real_backend.RealAWSClients.from_environment("eu-west-1")
    assert a.ga._client._provider is b.route53._client._provider
    assert a.elbv2._client._provider is b.elbv2._client._provider


class TestMalformedResponses:
    """Malformed-response hardening (VERDICT r4 #5): truncated/garbage
    JSON and XML, wrong content (HTML error pages, wrong-protocol
    documents), and half-written error envelopes must ALL surface as a
    diagnosable ``AWSAPIError`` naming the operation — never a raw
    ``json.JSONDecodeError`` / ``ET.ParseError`` / ``AttributeError``
    traceback into the reconcile loop, which would retry it forever as
    an anonymous error.  The analog of aws-sdk-go-v2's deserialization
    error wrapping the reference gets from the SDK (go.mod:8-13)."""

    HTML = b"<html><body><h1>502 Bad Gateway</h1></body></html>"

    # --- helpers ---------------------------------------------------------

    def ga(self):
        stub = StubTransport()
        # attempts=3 on purpose: a deserialization failure is NOT a
        # transport failure and must not be retried — one queued
        # response is enough (a retry would pop an empty queue)
        return (
            RealGlobalAcceleratorAPI(
                credentials=CREDS, transport=stub, sleep=lambda _: None
            ),
            stub,
        )

    def elbv2(self):
        stub = StubTransport()
        return (
            RealELBv2API(
                "us-west-2", credentials=CREDS, transport=stub, sleep=lambda _: None
            ),
            stub,
        )

    def r53(self):
        stub = StubTransport()
        return (
            RealRoute53API(credentials=CREDS, transport=stub, sleep=lambda _: None),
            stub,
        )

    def assert_deserialization_error(self, exc_info, operation):
        err = exc_info.value
        assert err.code == "DeserializationError"
        assert operation in str(err), f"operation not named: {err}"

    # --- Global Accelerator (JSON 1.1) -----------------------------------

    @pytest.mark.parametrize(
        "body",
        [
            b'{"Accelerators": [{',          # truncated mid-object
            b"\x00\xff\xfenot json at all",  # binary garbage
            b"<html><body>502</body></html>",  # wrong content type
            b'"just a string"',              # valid JSON, not an object
            b"[1, 2, 3]",                    # valid JSON, wrong top-level type
        ],
    )
    def test_ga_unparseable_bodies(self, body):
        client, stub = self.ga()
        stub.queue(200, body)
        with pytest.raises(AWSAPIError) as exc:
            client.list_accelerators(100, None)
        self.assert_deserialization_error(exc, "ListAccelerators")
        assert len(stub.requests) == 1  # no retry for deserialization

    @pytest.mark.parametrize(
        "operation,call,body",
        [
            (
                "ListAccelerators",
                lambda c: c.list_accelerators(100, None),
                {"Accelerators": "not-a-list-of-objects"},
            ),
            (
                "DescribeAccelerator",
                lambda c: c.describe_accelerator("arn:x"),
                {"Accelerator": [1, 2]},
            ),
            (
                "ListListeners",
                lambda c: c.list_listeners("arn:x", 100, None),
                {"Listeners": [{"PortRanges": [5]}]},
            ),
            (
                "DescribeEndpointGroup",
                lambda c: c.describe_endpoint_group("arn:x"),
                {"EndpointGroup": {"EndpointDescriptions": ["bare-string"]}},
            ),
            (
                "AddEndpoints",
                lambda c: c.add_endpoints("arn:x", []),
                {"EndpointDescriptions": [17]},
            ),
            (
                "ListTagsForResource",
                lambda c: c.list_tags_for_resource("arn:x"),
                {"Tags": ["oops"]},
            ),
        ],
    )
    def test_ga_wrong_shapes(self, operation, call, body):
        client, stub = self.ga()
        stub.queue(200, body)
        with pytest.raises(AWSAPIError) as exc:
            call(client)
        self.assert_deserialization_error(exc, operation)

    def test_ga_half_written_error_envelope(self):
        client, stub = self.ga()
        stub.queue(400, b'{"__type":"SomeError","mess')  # torn mid-key
        with pytest.raises(AWSAPIError) as exc:
            client.describe_accelerator("arn:x")
        # typed, names the operation, carries the body excerpt
        assert exc.value.code == "UnknownError"
        assert "DescribeAccelerator" in str(exc.value)
        assert "mess" in str(exc.value)

    def test_ga_error_envelope_that_is_not_an_object(self):
        client, stub = self.ga()
        stub.queue(400, b'["an", "array"]')
        with pytest.raises(AWSAPIError) as exc:
            client.delete_accelerator("arn:x")
        assert exc.value.code == "UnknownError"
        assert "DeleteAccelerator" in str(exc.value)

    # --- ELBv2 (Query XML) ------------------------------------------------

    @pytest.mark.parametrize(
        "body",
        [
            b"<DescribeLoadBalancersResponse><LoadBalancers><member>",  # truncated
            b"\x00\xff binary garbage",
            b'{"json": "not xml"}',
        ],
    )
    def test_elbv2_unparseable_bodies(self, body):
        client, stub = self.elbv2()
        stub.queue(200, body)
        with pytest.raises(AWSAPIError) as exc:
            client.describe_load_balancers(["my-lb"])
        self.assert_deserialization_error(exc, "DescribeLoadBalancers")
        assert len(stub.requests) == 1

    def test_elbv2_html_page_rejected_not_silently_empty(self):
        """An HTML error page IS well-formed XML; without root-tag
        validation it would parse to an empty LB list — absence where
        the truth is 'the response was garbage'."""
        client, stub = self.elbv2()
        stub.queue(200, self.HTML)
        with pytest.raises(AWSAPIError) as exc:
            client.describe_load_balancers(["my-lb"])
        self.assert_deserialization_error(exc, "DescribeLoadBalancers")
        assert "html" in str(exc.value)

    def test_elbv2_half_written_error_envelope(self):
        client, stub = self.elbv2()
        stub.queue(400, b"<ErrorResponse><Error><Code>Val")  # torn
        with pytest.raises(AWSAPIError) as exc:
            client.describe_load_balancers(["my-lb"])
        assert exc.value.code == "UnknownError"
        assert "DescribeLoadBalancers" in str(exc.value)

    # --- Route53 (REST XML) -----------------------------------------------

    def test_route53_garbage_body(self):
        client, stub = self.r53()
        stub.queue(200, b"%%% not xml %%%")
        with pytest.raises(AWSAPIError) as exc:
            client.list_hosted_zones(100, None)
        self.assert_deserialization_error(exc, "ListHostedZones")

    def test_route53_html_page_rejected(self):
        client, stub = self.r53()
        stub.queue(200, self.HTML)
        with pytest.raises(AWSAPIError) as exc:
            client.list_hosted_zones_by_name("example.com.", 1)
        self.assert_deserialization_error(exc, "ListHostedZonesByName")

    def test_route53_wrong_document_rejected(self):
        """A valid response document for a DIFFERENT operation is
        still a deserialization error, not an empty result."""
        client, stub = self.r53()
        stub.queue(
            200,
            b'<?xml version="1.0"?><ListHostedZonesResponse '
            b'xmlns="https://route53.amazonaws.com/doc/2013-04-01/">'
            b"<HostedZones/></ListHostedZonesResponse>",
        )
        with pytest.raises(AWSAPIError) as exc:
            client.list_resource_record_sets("/hostedzone/Z1", 300, None)
        self.assert_deserialization_error(exc, "ListResourceRecordSets")

    def test_route53_non_numeric_ttl(self):
        client, stub = self.r53()
        stub.queue(
            200,
            b'<?xml version="1.0"?><ListResourceRecordSetsResponse '
            b'xmlns="https://route53.amazonaws.com/doc/2013-04-01/">'
            b"<ResourceRecordSets><ResourceRecordSet>"
            b"<Name>a.example.com.</Name><Type>TXT</Type><TTL>NaN</TTL>"
            b"</ResourceRecordSet></ResourceRecordSets>"
            b"<IsTruncated>false</IsTruncated></ListResourceRecordSetsResponse>",
        )
        with pytest.raises(AWSAPIError) as exc:
            client.list_resource_record_sets("/hostedzone/Z1", 300, None)
        self.assert_deserialization_error(exc, "ListResourceRecordSets")

    def test_route53_half_written_error_envelope(self):
        client, stub = self.r53()
        stub.queue(500, b"<ErrorResponse><Error><Co")
        # 500 IS retryable (transient), so exhaust the retry budget
        # with the same torn body each time
        stub.queue(500, b"<ErrorResponse><Error><Co")
        stub.queue(500, b"<ErrorResponse><Error><Co")
        with pytest.raises(AWSAPIError) as exc:
            client.list_hosted_zones(100, None)
        assert exc.value.code == "UnknownError"
        assert "ListHostedZones" in str(exc.value)
