"""Shared object builders — the analog of the reference's
``pkg/fixture`` and ``local_e2e/pkg/fixtures`` packages."""

from __future__ import annotations

from agac_tpu import apis
from agac_tpu.cluster import ObjectMeta, Service, ServicePort
from agac_tpu.cluster.objects import (
    HTTPIngressPath,
    HTTPIngressRuleValue,
    Ingress,
    IngressBackend,
    IngressLoadBalancerIngress,
    IngressRule,
    IngressServiceBackend,
    IngressSpec,
    LoadBalancerIngress,
    ServiceBackendPort,
    ServiceSpec,
)

NLB_HOSTNAME = "testlb-0123456789abcdef.elb.us-west-2.amazonaws.com"
NLB_NAME = "testlb"
NLB_REGION = "us-west-2"

ALB_HOSTNAME = "k8s-default-testing-0a1b2c3d4e-111222333.us-west-2.elb.amazonaws.com"
ALB_NAME = "k8s-default-testing-0a1b2c3d4e"


def make_lb_service(
    name="web",
    ns="default",
    managed=True,
    hostname=NLB_HOSTNAME,
    ports=((80, "TCP"),),
    annotations=None,
):
    """An NLB Service like the reference's e2e fixture
    (``local_e2e/pkg/fixtures/service.go:10-51``)."""
    meta_annotations = {apis.AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external"}
    if managed:
        meta_annotations[apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION] = "true"
    meta_annotations.update(annotations or {})
    svc = Service(
        metadata=ObjectMeta(name=name, namespace=ns, annotations=meta_annotations),
        spec=ServiceSpec(
            type="LoadBalancer",
            ports=[ServicePort(name=f"p{port}", port=port, protocol=proto) for port, proto in ports],
        ),
    )
    if hostname:
        svc.status.load_balancer.ingress.append(LoadBalancerIngress(hostname=hostname))
    return svc


def make_alb_ingress(
    name="webapp",
    ns="default",
    managed=True,
    hostname=ALB_HOSTNAME,
    rule_ports=(80,),
    annotations=None,
):
    """An ALB Ingress like the reference's e2e fixture
    (``local_e2e/pkg/fixtures/ingress.go:15-58``)."""
    meta_annotations = {apis.INGRESS_CLASS_ANNOTATION: "alb"}
    if managed:
        meta_annotations[apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION] = "true"
    meta_annotations.update(annotations or {})
    ing = Ingress(
        metadata=ObjectMeta(name=name, namespace=ns, annotations=meta_annotations),
        spec=IngressSpec(
            ingress_class_name="alb",
            rules=[
                IngressRule(
                    host="app.example.com",
                    http=HTTPIngressRuleValue(
                        paths=[
                            HTTPIngressPath(
                                path="/",
                                backend=IngressBackend(
                                    service=IngressServiceBackend(
                                        name="backend",
                                        port=ServiceBackendPort(number=p),
                                    )
                                ),
                            )
                            for p in rule_ports
                        ]
                    ),
                )
            ],
        ),
    )
    if hostname:
        ing.status.load_balancer.ingress.append(
            IngressLoadBalancerIngress(hostname=hostname)
        )
    return ing
