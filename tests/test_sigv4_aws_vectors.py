"""Byte-level SigV4 validation against AWS's OWN published vectors
(VERDICT r1 next#2: "assert byte-level SigV4 signatures against AWS's
published test vectors").

These constants were not produced by this repo's code — they are
transcribed from AWS's Signature Version 4 documentation ("Deriving
the signing key" examples, the complete IAM ListUsers signing
walkthrough) and the aws-sig-v4-test-suite (get-vanilla /
get-vanilla-query-order-key-case, asserted in
``tests/test_real_aws_backend.py``).  Agreement here means the signing
path matches an implementation the author didn't write; a wrong
canonicalization, derivation chain, or scope string fails these
byte-for-byte.

The reference delegates all of this to aws-sdk-go-v2 (SURVEY.md §2
row 12); this repo hand-rolls it (``sigv4.py``), so the external
vectors carry the correctness burden the SDK carried there.
"""

import datetime

from agac_tpu.cloudprovider.aws.sigv4 import (
    Credentials,
    derive_signing_key,
    sign_request,
)

# The aws-sig-v4-test-suite / AWS docs example credentials.
ACCESS_KEY = "AKIDEXAMPLE"
SECRET_KEY = "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY"


class TestKeyDerivationVectors:
    """AWS docs, "Deriving the signing key" — published example
    outputs of the HMAC chain."""

    def test_derivation_example_20150830_iam(self):
        key = derive_signing_key(SECRET_KEY, "20150830", "us-east-1", "iam")
        assert key.hex() == (
            "c4afb1cc5771d871763a393e44b703571b55cc28424d1a5e86da6ed3c154a4b9"
        )

    def test_derivation_example_20120215_iam(self):
        key = derive_signing_key(SECRET_KEY, "20120215", "us-east-1", "iam")
        assert key.hex() == (
            "f4780e2d9f65fa895f9c67b32ce1baf0b0d8a43505a000a1a9e090d414db404d"
        )


class TestCompleteSigningExample:
    """AWS docs, the complete SigV4 walkthrough: GET ListUsers against
    IAM at 20150830T123600Z.  The published final signature commits to
    every intermediate (canonical request, hashed canonical request
    f536975d06c0309214f805bb90ccff089219ecd68b2577efef23edd43b7e1a59,
    string to sign, signing key)."""

    def test_iam_list_users_signature(self):
        now = datetime.datetime(2015, 8, 30, 12, 36, 0, tzinfo=datetime.timezone.utc)
        signed = sign_request(
            "GET",
            "https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08",
            {"Content-Type": "application/x-www-form-urlencoded; charset=utf-8"},
            b"",
            "iam",
            "us-east-1",
            Credentials(ACCESS_KEY, SECRET_KEY),
            now=now,
        )
        assert signed["Authorization"] == (
            "AWS4-HMAC-SHA256 "
            "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request, "
            "SignedHeaders=content-type;host;x-amz-date, "
            "Signature="
            "5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7"
        )
        assert signed["X-Amz-Date"] == "20150830T123600Z"
        assert signed["Host"] == "iam.amazonaws.com"
