"""CI guard for the opt-in real-apiserver (kind) tier: run
``tests/test_kind_e2e.py`` in smoke mode — the in-repo test apiserver
standing in for kind — in a subprocess so the tier's harness logic
(fixtures, CRD/client wiring, subprocess controller drive, polling)
can't rot between real-cluster runs.  The pattern mirror of
``tests/test_real_aws_harness_smoke.py``; the real tier itself needs
kind+docker (``hack/kind-e2e.sh``, reference
``.github/workflows/e2e.yml:22-24``) and never runs here.

Smoke mode's guaranteed floor: 4 protocol-shaped tests pass (typed
CRUD/status/finalizers, informer list-watch-resync, full controller
subprocess drive, embedded-apiserver restart soak); the 3 that require
genuine apiserver features (apiextensions Established, admission
registration over TLS, node restart) skip with explicit reasons —
they are the real tier's job.
"""

import os
import pathlib
import re
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def _counts(stdout: str) -> dict:
    """Exact {outcome: N} from the pytest summary line — substring
    checks would let '14 passed' satisfy a '4 passed' floor."""
    return {
        outcome: int(n)
        for n, outcome in re.findall(r"(\d+) (passed|failed|skipped|error)", stdout)
    }


def test_kind_harness_passes_in_smoke_mode():
    env = dict(os.environ, E2E_KIND="smoke")
    env.pop("KUBECONFIG", None)
    env.pop("E2E_KIND_SOAK", None)
    result = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_kind_e2e.py", "-q"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    # the floor is exact: a new smoke-capable test must pass, a new
    # real-only test must carry its own skip reason
    assert _counts(result.stdout) == {"passed": 4, "skipped": 3}, result.stdout


def test_kind_harness_skips_by_default():
    env = dict(os.environ)
    env.pop("E2E_KIND", None)
    result = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_kind_e2e.py", "-q"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert _counts(result.stdout) == {"skipped": 7}, result.stdout
