"""Opt-in full-loop e2e against REAL AWS — the analog of the
reference's ``local_e2e/`` suite (``local_e2e/e2e_test.go:257-385``,
``local_e2e/README.md``): drive an annotated Service through the real
controllers until a real accelerator → listener → endpoint-group chain
(and optionally Route53 alias records) converges, then delete and poll
until AWS is clean.  The production driver is reused as the test
oracle, exactly as the reference reuses its ``cloudprovider/aws``
(``e2e_test.go:13,119-122``).

NEVER runs in CI.  Gated on ``E2E_AWS=1`` plus credentials; the
Kubernetes side is the in-process fake cluster (the real-apiserver
tier lives in ``tests/test_kind_e2e.py``) because the subject under
test here is the REAL AWS wire path: SigV4 signing, GA JSON-RPC,
ELBv2/Route53 XML, pagination, error mapping — everything
``real_backend.py`` encodes from documentation rather than from an SDK.

Environment contract (mirrors ``local_e2e/e2e_test.go:46-58``):

- ``E2E_AWS=1``                 — opt-in gate.
- AWS credentials               — any mechanism the production chain
                                  resolves (env keys, IRSA, shared
                                  credentials file).
- ``E2E_LB_HOSTNAME``           — DNS name of an EXISTING NLB/ALB in
                                  your account (the reference gets one
                                  from its kops cluster; here you
                                  bring your own).
- ``E2E_ROUTE53_HOSTNAME``      — optional: hostname inside a hosted
                                  zone you own; enables the Route53
                                  assertions (comma-separated ok).
- ``E2E_CLUSTER_NAME``          — ownership-tag namespace (default
                                  ``agac-e2e``).

Cost: a Global Accelerator bills ~$0.025/hour plus data transfer from
creation until deletion; a complete run creates exactly one and
deletes it again within the run (typically < 15 min → well under
$0.01), plus a handful of Route53 API calls (free) and two records
(deleted again).  A FAILED run can leave the accelerator behind —
clean up with the AWS console or
``aws globalaccelerator list-accelerators`` if the teardown assertions
did not complete.

Run: ``make e2e-aws`` (or
``E2E_AWS=1 E2E_LB_HOSTNAME=... python -m pytest tests/test_real_aws_e2e.py -s``).
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from agac_tpu import apis

# E2E_AWS=1      → real AWS (credentials + E2E_LB_HOSTNAME required).
# E2E_AWS=smoke  → same harness against the in-repo fake backend with
#                  tight polling: verifies the HARNESS logic (fixture
#                  wiring, oracle polling, teardown ordering) without
#                  credentials, so the real tier can't rot unnoticed.
#                  tests/test_real_aws_harness_smoke.py runs this in CI.
E2E_MODE = os.environ.get("E2E_AWS", "")
SMOKE = E2E_MODE == "smoke"

pytestmark = pytest.mark.skipif(
    E2E_MODE not in ("1", "smoke"),
    reason="real-AWS e2e is opt-in: set E2E_AWS=1 plus credentials and "
    "E2E_LB_HOSTNAME (see module docstring for the full contract and cost)",
)

# reference polling budgets: 10 s interval, 5-10 min timeouts
# (``local_e2e/e2e_test.go:102,264,317,355,372``)
POLL_INTERVAL = 0.05 if SMOKE else 10.0
CONVERGE_TIMEOUT = 10.0 if SMOKE else 600.0
ROUTE53_TIMEOUT = 10.0 if SMOKE else 300.0
CLEANUP_TIMEOUT = 10.0 if SMOKE else 600.0


def poll_until(description: str, pred, timeout: float):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        print(f"waiting: {description}")
        time.sleep(POLL_INTERVAL)
    assert pred(), f"timed out after {timeout}s waiting for {description}"


@pytest.fixture(scope="module")
def env():
    if SMOKE:
        from .fixtures import NLB_HOSTNAME

        return {
            "lb_hostname": NLB_HOSTNAME,
            "route53_hostname": "app.example.com",
            "cluster_name": "agac-e2e",
        }
    lb_hostname = os.environ.get("E2E_LB_HOSTNAME")
    assert lb_hostname, "E2E_LB_HOSTNAME is required (existing NLB/ALB DNS name)"
    return {
        "lb_hostname": lb_hostname,
        "route53_hostname": os.environ.get("E2E_ROUTE53_HOSTNAME", ""),
        "cluster_name": os.environ.get("E2E_CLUSTER_NAME", "agac-e2e"),
    }


@pytest.fixture(scope="module")
def stack(env):
    """Manager + controllers on the fake cluster, production cloud
    factory (real SigV4 backend) — the deployment the reference makes
    in-cluster (``local_e2e/pkg/fixtures/manager.go:16-108``), run
    in-process instead."""
    from agac_tpu.cloudprovider.aws.factory import real_cloud_factory
    from agac_tpu.cluster import FakeCluster
    from agac_tpu.controllers.endpointgroupbinding import EndpointGroupBindingConfig
    from agac_tpu.controllers.globalaccelerator import GlobalAcceleratorConfig
    from agac_tpu.controllers.route53 import Route53Config
    from agac_tpu.manager import ControllerConfig, Manager

    if SMOKE:
        from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
        from agac_tpu.cloudprovider.aws import get_lb_name_from_hostname

        backend = FakeAWSBackend()
        lb_name, lb_region = get_lb_name_from_hostname(env["lb_hostname"])
        backend.add_load_balancer(lb_name, lb_region, env["lb_hostname"])
        backend.add_hosted_zone("example.com")
        factory = lambda region: AWSDriver(  # noqa: E731
            backend, backend, backend, poll_interval=0.01, poll_timeout=2.0,
            lb_not_active_retry=0.05, accelerator_missing_retry=0.05,
        )
    else:
        assert os.environ.get("AGAC_CLOUD") != "fake", (
            "unset AGAC_CLOUD: this tier exists to exercise the REAL backend"
        )
        factory = real_cloud_factory
    name = env["cluster_name"]
    cluster = FakeCluster()
    stop = threading.Event()
    Manager(resync_period=0.3 if SMOKE else 30.0).run(
        cluster,
        ControllerConfig(
            global_accelerator=GlobalAcceleratorConfig(cluster_name=name),
            route53=Route53Config(cluster_name=name),
            endpoint_group_binding=EndpointGroupBindingConfig(),
        ),
        stop,
        cloud_factory=factory,
        block=False,
    )
    yield {"cluster": cluster, "factory": factory}
    stop.set()


def _oracle(factory):
    """The production driver as oracle, GA/Route53 pinned global."""
    from agac_tpu.controllers.common import GLOBAL_REGION

    return factory(GLOBAL_REGION)


def test_service_chain_converges_and_cleans_up(env, stack):
    from agac_tpu.cloudprovider.aws import get_lb_name_from_hostname
    from agac_tpu.cloudprovider.aws.driver import Route53OwnerValue
    from agac_tpu.cloudprovider.aws.errors import (
        EndpointGroupNotFoundException,
        ListenerNotFoundException,
    )

    from .fixtures import make_lb_service

    cluster = stack["cluster"]
    factory = stack["factory"]
    cloud = _oracle(factory)

    lb_name, lb_region = get_lb_name_from_hostname(env["lb_hostname"])
    lb = factory(lb_region).get_load_balancer(lb_name)

    annotations = {}
    hostnames = []
    if env["route53_hostname"]:
        annotations[apis.ROUTE53_HOSTNAME_ANNOTATION] = env["route53_hostname"]
        hostnames = env["route53_hostname"].split(",")

    svc = make_lb_service(
        name="agac-e2e-test", hostname=env["lb_hostname"], annotations=annotations
    )
    cluster.create("Service", svc)

    def list_owned():
        return cloud.list_global_accelerator_by_resource(
            env["cluster_name"], "service", "default", "agac-e2e-test"
        )

    try:
        # --- converge: accelerator → listener → endpoint group whose
        # endpoint is OUR load balancer (``e2e_test.go:257-303``)
        def chain_converged():
            for accelerator in list_owned():
                try:
                    listener = cloud.get_listener(accelerator.accelerator_arn)
                    group = cloud.get_endpoint_group(listener.listener_arn)
                except (ListenerNotFoundException, EndpointGroupNotFoundException):
                    return False
                if any(
                    d.endpoint_id == lb.load_balancer_arn
                    for d in group.endpoint_descriptions
                ):
                    return True
            return False

        poll_until("accelerator chain", chain_converged, CONVERGE_TIMEOUT)

        # --- Route53 alias records point at the accelerator
        # (``e2e_test.go:305-340``)
        if hostnames:
            accelerator = list_owned()[0]
            owner = Route53OwnerValue(
                env["cluster_name"], "service", "default", "agac-e2e-test"
            )

            def records_converged():
                for h in hostnames:
                    zone = cloud.get_hosted_zone(h)
                    records = cloud.find_owned_a_record_sets(zone, owner)
                    if not any(
                        r.alias_target is not None
                        and r.alias_target.dns_name == accelerator.dns_name + "."
                        for r in records
                    ):
                        return False
                return True

            poll_until("route53 alias records", records_converged, ROUTE53_TIMEOUT)
    finally:
        # --- teardown: delete the Service, poll AWS until clean
        # (``e2e_test.go:342-385``); runs even when convergence failed
        # so a broken run still tries to avoid leaking an accelerator
        cluster.delete("Service", "default", "agac-e2e-test")

    poll_until("accelerator cleanup", lambda: list_owned() == [], CLEANUP_TIMEOUT)
    if hostnames:
        owner = Route53OwnerValue(
            env["cluster_name"], "service", "default", "agac-e2e-test"
        )

        def records_gone():
            return all(
                cloud.find_owned_a_record_sets(cloud.get_hosted_zone(h), owner) == []
                for h in hostnames
            )

        poll_until("route53 cleanup", records_gone, CLEANUP_TIMEOUT)
