"""Unit tier for the observability plane (ISSUE 5):
``agac_tpu/observability/`` — registry thread-safety, histogram bucket
math, the exposition-format golden test, the label-cardinality cap,
span lifecycle + sampling on a fake clock, flight-recorder wraparound,
and the ``/metrics`` + ``/debug/flightrecorder`` endpoints on the
manager's health server.  The live fault-injected scrape lives in
``tests/test_chaos_e2e.py``.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from agac_tpu.manager import make_health_server
from agac_tpu.observability import trace as trace_mod
from agac_tpu.observability.catalog import BEGIN, END, render_table
from agac_tpu.observability.instruments import instrument_api, register_all
from agac_tpu.observability.metrics import (
    CONTENT_TYPE,
    MetricsRegistry,
    parse_text,
)
from agac_tpu.observability.recorder import FlightRecorder
from agac_tpu.observability.trace import Tracer
from agac_tpu.reconcile import RateLimitingQueue, process_next_work_item
from agac_tpu.reconcile.result import Result


class FakeClock:
    def __init__(self, now: float = 100.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_thread_safety_under_concurrent_increments(self):
        reg = MetricsRegistry()
        counter = reg.counter("t_total", "t", labels=("who",))
        child = counter.labels(who="x")
        n_threads, n_incs = 8, 2000

        def worker():
            for _ in range(n_incs):
                child.inc()

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert child.value() == n_threads * n_incs

    def test_get_or_create_returns_the_same_metric(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", "x")
        b = reg.counter("x_total", "x")
        assert a is b

    def test_type_or_label_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.counter("x_total", "x")
        with pytest.raises(ValueError):
            reg.gauge("x_total", "x")
        with pytest.raises(ValueError):
            reg.counter("x_total", "x", labels=("a",))

    def test_counters_refuse_to_go_down(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("x_total", "x").inc(-1)

    def test_wrong_label_names_raise(self):
        reg = MetricsRegistry()
        metric = reg.counter("x_total", "x", labels=("a",))
        with pytest.raises(ValueError):
            metric.labels(b="1")

    def test_histogram_bucket_math(self):
        reg = MetricsRegistry()
        hist = reg.histogram("lat_seconds", "l", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            hist.observe(v)
        samples = parse_text(reg.render())
        # buckets are CUMULATIVE: le=0.1 holds 1, le=1 holds 3, ...
        assert samples['lat_seconds_bucket{le="0.1"}'] == 1
        assert samples['lat_seconds_bucket{le="1"}'] == 3
        assert samples['lat_seconds_bucket{le="10"}'] == 4
        assert samples['lat_seconds_bucket{le="+Inf"}'] == 5
        assert samples["lat_seconds_count"] == 5
        assert samples["lat_seconds_sum"] == pytest.approx(56.05)

    def test_exposition_format_golden(self):
        """The exact text a scraper sees: HELP/TYPE headers, sorted
        families, label escaping, histogram expansion."""
        reg = MetricsRegistry()
        reg.counter("b_total", "b counts", labels=("op",)).labels(op="x").inc(3)
        reg.gauge("a_depth", "a depth").set(2)
        hist = reg.histogram("c_seconds", "c latency", buckets=(0.5, 1.0))
        hist.observe(0.25)
        assert reg.render() == (
            "# HELP a_depth a depth\n"
            "# TYPE a_depth gauge\n"
            "a_depth 2\n"
            "# HELP b_total b counts\n"
            "# TYPE b_total counter\n"
            'b_total{op="x"} 3\n'
            "# HELP c_seconds c latency\n"
            "# TYPE c_seconds histogram\n"
            'c_seconds_bucket{le="0.5"} 1\n'
            'c_seconds_bucket{le="1"} 1\n'
            'c_seconds_bucket{le="+Inf"} 1\n'
            "c_seconds_sum 0.25\n"
            "c_seconds_count 1\n"
        )

    def test_label_value_escaping(self):
        reg = MetricsRegistry()
        reg.counter("esc_total", "e", labels=("k",)).labels(k='a"b\\c\nd').inc()
        line = [
            l for l in reg.render().splitlines() if l.startswith("esc_total{")
        ][0]
        assert line == 'esc_total{k="a\\"b\\\\c\\nd"} 1'

    def test_label_cardinality_cap_collapses_to_overflow(self):
        reg = MetricsRegistry(max_series=3)
        metric = reg.counter("capped_total", "c", labels=("key",))
        for i in range(10):
            metric.labels(key=f"k{i}").inc()
        samples = {
            name: v
            for name, v in parse_text(reg.render()).items()
            if name.startswith("capped_total")
        }
        # 3 real series + ONE overflow series absorbing the other 7
        assert len(samples) == 4
        assert samples['capped_total{key="overflow"}'] == 7
        assert metric.dropped_series == 7

    def test_gauge_callback_is_a_live_view(self):
        reg = MetricsRegistry()
        state = {"v": 1.0}
        reg.gauge("live", "l").set_function(lambda: state["v"])
        assert parse_text(reg.render())["live"] == 1
        state["v"] = 7.0
        assert parse_text(reg.render())["live"] == 7

    def test_callback_failure_renders_nan_not_crash(self):
        reg = MetricsRegistry()
        reg.gauge("bad", "b").set_function(lambda: 1 / 0)
        assert "bad NaN" in reg.render()

    def test_catalog_table_covers_every_registered_metric(self):
        reg = register_all(MetricsRegistry())
        table = render_table()
        for desc in reg.describe():
            assert f"`{desc['name']}`" in table
        # the committed doc carries the generated block current
        import pathlib

        doc = (
            pathlib.Path(__file__).resolve().parent.parent / "docs" / "operations.md"
        ).read_text()
        assert BEGIN in doc and END in doc
        assert table in doc, "docs/operations.md catalog is stale — run `make metrics-catalog`"


# ---------------------------------------------------------------------------
# trace spans
# ---------------------------------------------------------------------------


class TestTrace:
    def test_span_lifecycle_on_a_fake_clock(self):
        clock = FakeClock()
        emitted = []
        tracer = Tracer(sample_rate=1.0, clock=clock, emit=emitted.append)
        tr = tracer.start("ctrl", "ns/obj", queue_wait=0.5)
        assert tr is not None
        with trace_mod.activate(tr):
            with trace_mod.span("sync"):
                clock.advance(2.0)
                trace_mod.record_call(
                    "globalaccelerator", "list_accelerators",
                    clock.now - 0.25, clock.now, "success",
                )
        tr.annotate(result="success")
        clock.advance(0.5)
        tracer.finish(tr)
        assert len(emitted) == 1
        payload = emitted[0]
        assert payload["controller"] == "ctrl"
        assert payload["key"] == "ns/obj"
        assert payload["result"] == "success"
        assert payload["dur"] == pytest.approx(2.5)
        spans = {s["name"]: s for s in payload["spans"]}
        assert spans["queue-wait"]["dur"] == pytest.approx(0.5)
        assert spans["sync"]["dur"] == pytest.approx(2.0)
        aws = spans["aws:globalaccelerator.list_accelerators"]
        assert aws["dur"] == pytest.approx(0.25)
        assert aws["attrs"]["outcome"] == "success"

    def test_sampling_is_deterministic_every_nth(self):
        tracer = Tracer(sample_rate=0.25, clock=FakeClock())
        sampled = [tracer.start("c", f"k{i}") is not None for i in range(8)]
        assert sampled == [False, False, False, True] * 2

    def test_rate_zero_disables(self):
        tracer = Tracer(sample_rate=0.0)
        assert all(tracer.start("c", "k") is None for _ in range(5))

    def test_unsampled_path_is_a_noop_everywhere(self):
        tracer = Tracer(sample_rate=0.0)
        tr = tracer.start("c", "k")
        with trace_mod.activate(tr):
            assert trace_mod.current() is None
            with trace_mod.span("sync"):
                trace_mod.record_call("ga", "op", 0.0, 1.0, "success")
        tracer.finish(tr)  # must not raise or emit
        assert tracer.emitted_total == 0

    def test_span_records_exception_and_still_closes(self):
        clock = FakeClock()
        tracer = Tracer(sample_rate=1.0, clock=clock, emit=lambda p: None)
        tr = tracer.start("c", "k")
        with trace_mod.activate(tr):
            with pytest.raises(RuntimeError):
                with trace_mod.span("settle-poll", arn="a1"):
                    clock.advance(1.0)
                    raise RuntimeError("boom")
        assert tr.spans[-1].name == "settle-poll"
        assert tr.spans[-1].duration() == pytest.approx(1.0)
        assert "boom" in tr.spans[-1].attrs["error"]

    def test_emit_failure_is_contained(self):
        def bad_emit(payload):
            raise RuntimeError("collector down")

        tracer = Tracer(sample_rate=1.0, emit=bad_emit)
        tracer.finish(tracer.start("c", "k"))  # must not raise


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_wraparound_keeps_the_newest_entries_in_order(self):
        clock = FakeClock()
        recorder = FlightRecorder(capacity=4, clock=clock)
        for i in range(10):
            clock.advance(1.0)
            recorder.record("reconcile", key=f"k{i}")
        assert len(recorder) == 4
        entries = recorder.dump()
        assert [e["key"] for e in entries] == ["k6", "k7", "k8", "k9"]
        assert [e["seq"] for e in entries] == [7, 8, 9, 10]
        assert entries[0]["time"] < entries[-1]["time"]
        assert recorder.recorded_total == 10

    def test_dump_limit_takes_the_tail(self):
        recorder = FlightRecorder(capacity=8)
        for i in range(5):
            recorder.record("reconcile", key=f"k{i}")
        assert [e["key"] for e in recorder.dump(limit=2)] == ["k3", "k4"]

    def test_record_never_raises_on_unserializable_fields(self):
        recorder = FlightRecorder(capacity=2)
        recorder.record("reconcile", obj=object())  # stored as-is, no raise
        assert len(recorder) == 1

    def test_reason_and_ring_epoch_ride_through_to_the_dump(self):
        # the explain plane's timeline (ISSUE 15) reads these fields
        # straight off the dump — they must survive verbatim
        recorder = FlightRecorder(capacity=4)
        recorder.record(
            "reconcile", controller="ctrl", key="ns/app",
            result="requeued", reason="circuit-open", ring_epoch=3,
        )
        entry = recorder.dump()[-1]
        assert entry["reason"] == "circuit-open"
        assert entry["ring_epoch"] == 3


# ---------------------------------------------------------------------------
# SIGTERM post-mortem: the blocked-on table
# ---------------------------------------------------------------------------


class TestSigtermPostMortem:
    def test_handler_appends_the_top_blocked_on_table(self):
        """The terminating pod's log gains one line per blocked-on
        verdict (ISSUE 15), alongside the flight-recorder tail and the
        profiler top table — and the stop event still sets."""
        import logging
        import signal as signal_mod

        from agac_tpu import signals
        from agac_tpu.observability import explain, journey

        clock = FakeClock()
        reg = MetricsRegistry()
        journeys = journey.JourneyTracker(registry=reg, clock=clock)
        queue = RateLimitingQueue(name="pm", clock=clock, metrics_registry=reg)
        engine = explain.ExplainEngine(journeys=journeys, clock=clock)
        engine.register_worker("ctrl", queue, lambda key: object(), managed=None)
        journeys.observe_enqueued("ctrl", "ns/a")
        queue.add_after("ns/a", 30.0, reason="circuit-open")
        journeys.observe_enqueued("ctrl", "ns/b")

        records: list[str] = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = Capture()
        logger = logging.getLogger("agac")
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        previous_engine = explain.install(engine)
        saved_installed = signals._installed
        saved_int = signal_mod.getsignal(signal_mod.SIGINT)
        saved_term = signal_mod.getsignal(signal_mod.SIGTERM)
        signals._installed = False
        try:
            stop = signals.setup_signal_handler()
            signal_mod.raise_signal(signal_mod.SIGTERM)
            assert stop.is_set()
        finally:
            signal_mod.signal(signal_mod.SIGINT, saved_int)
            signal_mod.signal(signal_mod.SIGTERM, saved_term)
            signals._installed = saved_installed
            explain.install(previous_engine)
            logger.removeHandler(handler)

        table = [line for line in records if "blocked-on" in line]
        assert table, records
        assert "2 unconverged" in table[0]
        body = "\n".join(records)
        assert "circuit-open" in body and "in-flight" in body


# ---------------------------------------------------------------------------
# instrumented hot paths
# ---------------------------------------------------------------------------


class TestWorkqueueMetrics:
    def test_standard_metric_set_moves_through_the_lifecycle(self):
        clock = FakeClock()
        reg = MetricsRegistry()
        queue = RateLimitingQueue(name="obs-test", clock=clock, metrics_registry=reg)
        try:
            queue.add("a")
            queue.add("b")
            queue.add("b")  # coalesced: counts once
            samples = parse_text(reg.render())
            assert samples['agac_workqueue_adds_total{name="obs-test"}'] == 2
            assert samples['agac_workqueue_depth{name="obs-test"}'] == 2

            clock.advance(0.2)
            item, _ = queue.get()
            assert queue.last_pop_wait() == pytest.approx(0.2)
            clock.advance(0.05)
            queue.done(item)
            samples = parse_text(reg.render())
            assert samples['agac_workqueue_depth{name="obs-test"}'] == 1
            assert (
                samples['agac_workqueue_queue_duration_seconds_count{name="obs-test"}']
                == 1
            )
            assert samples[
                'agac_workqueue_queue_duration_seconds_sum{name="obs-test"}'
            ] == pytest.approx(0.2)
            assert samples[
                'agac_workqueue_work_duration_seconds_sum{name="obs-test"}'
            ] == pytest.approx(0.05)

            queue.add_rate_limited("a")
            samples = parse_text(reg.render())
            assert samples['agac_workqueue_retries_total{name="obs-test"}'] == 1
        finally:
            queue.shutdown()


class TestReconcileMetrics:
    def _drain(self, queue, process, registry=None):
        process_next_work_item(
            queue,
            key_to_obj=lambda key: {"key": key},
            process_delete=lambda key: Result(),
            process_create_or_update=process,
        )

    def test_result_counters_and_recorder_move(self):
        from agac_tpu.observability import instruments, metrics, recorder

        results = instruments.reconcile_instruments().results
        thread = threading.current_thread().name
        ok_child = results.labels(controller=thread, result="success")
        err_child = results.labels(controller=thread, result="error")
        ok_before, err_before = ok_child.value(), err_child.value()
        recorded_before = recorder.flight_recorder().recorded_total

        queue = RateLimitingQueue(name="obs-reconcile")
        try:
            queue.add("ns/ok")
            self._drain(queue, lambda obj: Result())
            queue.add("ns/bad")

            def boom(obj):
                raise RuntimeError("boom")

            self._drain(queue, boom)
        finally:
            queue.shutdown()

        assert ok_child.value() == ok_before + 1
        assert err_child.value() == err_before + 1
        flight = recorder.flight_recorder().dump()[-2:]
        assert [e["result"] for e in flight] == ["success", "error"]
        assert "boom" in flight[-1]["error"]

    def test_flight_recorder_entries_carry_the_journey_id(self):
        """The SLO plane's grep contract (ISSUE 9): a slow journey
        surfaced by /slo is found in the flight recorder BY ITS ID —
        every reconcile entry for an open journey carries it, and the
        converging pass closes the journey."""
        from agac_tpu.observability import journey, recorder

        controller = threading.current_thread().name
        tracker = journey.tracker()
        queue = RateLimitingQueue(name="obs-journey")
        try:
            tracker.observe_enqueued(controller, "ns/tracked", generation=2)
            journey_id = tracker.journey_id(controller, "ns/tracked")
            assert journey_id.startswith("ns/tracked@g2#")
            queue.add("ns/tracked")

            def requeue_once(obj):
                return Result(requeue=True)

            self._drain(queue, requeue_once)  # requeued: journey stays open
            self._drain(queue, lambda obj: Result())  # converges: closes
        finally:
            queue.shutdown()
        flight = recorder.flight_recorder().dump()[-2:]
        assert [e["journey"] for e in flight] == [journey_id, journey_id]
        assert [e["result"] for e in flight] == ["requeue", "success"]
        assert tracker.journey_id(controller, "ns/tracked") is None

    def test_untracked_items_record_an_empty_journey_field(self):
        from agac_tpu.observability import recorder

        queue = RateLimitingQueue(name="obs-nojourney")
        try:
            queue.add("ns/untracked")
            self._drain(queue, lambda obj: Result())
        finally:
            queue.shutdown()
        assert recorder.flight_recorder().dump()[-1]["journey"] == ""

    def test_sampled_reconcile_emits_a_trace_with_queue_wait(self):
        emitted = []
        tracer = trace_mod.tracer()
        old_emit = tracer._emit
        tracer._emit = emitted.append
        tracer.set_sample_rate(1.0)
        try:
            queue = RateLimitingQueue(name="obs-traced")
            queue.add("ns/traced")
            self._drain(queue, lambda obj: Result())
            queue.shutdown()
        finally:
            tracer._emit = old_emit
            tracer.set_sample_rate(0.0)
        assert len(emitted) == 1
        payload = emitted[0]
        assert payload["key"] == "ns/traced"
        assert payload["result"] == "success"
        span_names = [s["name"] for s in payload["spans"]]
        assert "queue-wait" in span_names and "sync" in span_names


class TestInstrumentedAPI:
    class FakeService:
        def list_accelerators(self, token=None):
            return [], None

        def create_accelerator(self, name):
            from agac_tpu.cloudprovider.aws.errors import AWSAPIError

            raise AWSAPIError("ThrottlingException", "slow down")

        def helper(self):
            return "passthrough"

    def test_calls_and_outcomes_are_counted_per_op(self):
        reg = MetricsRegistry()
        api = instrument_api(
            self.FakeService(),
            "globalaccelerator",
            frozenset({"list_accelerators", "create_accelerator"}),
            registry=reg,
        )
        api.list_accelerators()
        api.list_accelerators()
        with pytest.raises(Exception):
            api.create_accelerator("x")
        assert api.helper() == "passthrough"
        samples = parse_text(reg.render())
        assert samples[
            'agac_aws_api_calls_total{service="globalaccelerator",'
            'op="list_accelerators",outcome="success"}'
        ] == 2
        assert samples[
            'agac_aws_api_calls_total{service="globalaccelerator",'
            'op="create_accelerator",outcome="ThrottlingException"}'
        ] == 1
        assert samples[
            'agac_aws_api_call_duration_seconds_count'
            '{service="globalaccelerator",op="list_accelerators"}'
        ] == 2


# ---------------------------------------------------------------------------
# the health server endpoints
# ---------------------------------------------------------------------------


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as response:
        return response.status, response.headers.get("Content-Type"), response.read()


class TestServerEndpoints:
    def test_metrics_and_flightrecorder_endpoints(self):
        reg = MetricsRegistry()
        reg.counter("e2e_total", "e").inc(5)
        recorder = FlightRecorder(capacity=4)
        recorder.record("reconcile", key="ns/x", result="success")
        server = make_health_server(0, metrics_registry=reg, flight_recorder=recorder)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            status, ctype, body = _get(base + "/metrics")
            assert status == 200
            assert ctype == CONTENT_TYPE
            samples = parse_text(body.decode())
            assert samples["e2e_total"] == 5

            status, ctype, body = _get(base + "/debug/flightrecorder")
            assert status == 200
            dump = json.loads(body)
            assert dump["capacity"] == 4
            assert dump["entries"][0]["key"] == "ns/x"

            # the default fleet view serves this replica's own
            # registry under /metrics/fleet (peers come via
            # --fleet-peers); counters pass through unchanged
            status, ctype, body = _get(base + "/metrics/fleet")
            assert status == 200
            assert ctype == CONTENT_TYPE
            text = body.decode()
            assert "# fleet-sources: self" in text
            assert parse_text(text)["e2e_total"] == 5
        finally:
            server.shutdown()
            server.server_close()

    def test_slo_endpoint_and_healthz_block(self):
        """/slo serves the engine's full view and /healthz carries the
        summary block (ISSUE 9); without an installed engine both
        degrade to {"enabled": false}."""
        from agac_tpu.observability import journey as journey_mod
        from agac_tpu.observability import slo as slo_mod

        reg = MetricsRegistry()
        tracker = journey_mod.JourneyTracker(registry=reg)
        tracker.observe_enqueued(
            "global-accelerator-controller-service", "ns/a"
        )
        engine = slo_mod.SLOEngine(registry=reg, journey_tracker=tracker)
        engine.tick()
        server = make_health_server(0, slo_status=engine.status)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            status, _ctype, body = _get(base + "/slo")
            assert status == 200
            view = json.loads(body)
            assert view["enabled"] is True
            names = {o["name"] for o in view["objectives"]}
            assert "ga_converge_p99" in names and "drift_repair_p99" in names
            assert view["slowest_unconverged"][0]["key"] == "ns/a"

            status, _ctype, body = _get(base + "/healthz")
            assert json.loads(body)["slo"]["enabled"] is True
        finally:
            server.shutdown()
            server.server_close()

    def test_slo_endpoint_disabled_without_engine(self):
        from agac_tpu.observability import slo as slo_mod

        previous = slo_mod.install_engine(None)
        server = make_health_server(0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            status, _ctype, body = _get(base + "/slo")
            assert status == 200
            assert json.loads(body) == {"enabled": False}
        finally:
            slo_mod.install_engine(previous)
            server.shutdown()
            server.server_close()
