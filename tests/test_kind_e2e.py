"""Real-kube-apiserver e2e tier (VERDICT r1 next#1): prove the wire
protocol — CRD structural schema, status subresource, finalizers,
informer list/watch, leader-election Leases, Events, admission webhook
wiring — against an apiserver this repo's author did NOT write.

The analog of the reference's kind tier (``e2e/e2e_test.go:78-98``,
``hack/kind-with-registry.sh``, ``.github/workflows/e2e.yml:22-24``).

Modes (``E2E_KIND``):

- ``1``     — a real cluster: ``make e2e-kind`` (→ ``hack/kind-e2e.sh``)
              creates a kind cluster, generates webhook TLS material,
              and runs this file with KUBECONFIG + E2E_WEBHOOK_* set.
              Any genuine apiserver works (k3s/minikube): point
              KUBECONFIG at it.  CI: the ``kind`` job in
              ``.github/workflows/e2e.yml`` (3-version k8s matrix).
              Recorded runs + environment caveats: KIND_E2E_RESULTS.md.
- ``smoke`` — the in-repo test apiserver (``make e2e-kind-smoke``):
              validates this tier's OWN harness logic (fixtures,
              polling, subprocess drive) offline so it can't rot;
              protocol-proving tests that need real apiserver features
              (apiextensions, admission registration, TLS) skip
              themselves.  Runs inside ``make test`` via
              tests/test_kind_harness_smoke.py.
- unset     — skipped entirely.

Webhook env (set by hack/kind-e2e.sh for mode 1):
``E2E_WEBHOOK_URL`` (https URL the apiserver can reach this host at),
``E2E_WEBHOOK_CERT`` / ``E2E_WEBHOOK_KEY`` (PEM files for that host),
``E2E_WEBHOOK_CA_BUNDLE`` (base64 CA for the webhook configuration).

Soak (mode 1 only, ``E2E_KIND_SOAK=1``): restarts the kube-apiserver
inside the kind node and asserts the informer recovers with no drift
(reference resilience intent, ``local_e2e/e2e_test.go:102-205``).
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys
import threading
import time

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
E2E_MODE = os.environ.get("E2E_KIND", "")
SMOKE = E2E_MODE == "smoke"
REAL = E2E_MODE == "1"

pytestmark = pytest.mark.skipif(
    E2E_MODE not in ("1", "smoke"),
    reason="real-apiserver e2e is opt-in: run hack/kind-e2e.sh (E2E_KIND=1 "
    "+ KUBECONFIG), or E2E_KIND=smoke for the offline harness check",
)

POLL_TIMEOUT = 60.0 if REAL else 10.0


def wait_until(pred, timeout=POLL_TIMEOUT, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    """Smoke mode only: the in-repo apiserver."""
    if not SMOKE:
        yield None
        return
    from agac_tpu.cluster.testserver import TestApiServer

    with TestApiServer() as srv:
        yield srv


@pytest.fixture(scope="module")
def client(server):
    if SMOKE:
        from agac_tpu.cluster.rest import RestClusterClient

        return RestClusterClient(server.url)
    from agac_tpu.cluster.rest import build_client_from_kubeconfig

    kubeconfig = os.environ.get("KUBECONFIG")
    assert kubeconfig, "E2E_KIND=1 requires KUBECONFIG"
    return build_client_from_kubeconfig(kubeconfig)


@pytest.fixture(scope="module")
def dynamic(client):
    from agac_tpu.cluster.dynamic import DynamicClient

    return DynamicClient(client)


@pytest.fixture(scope="module")
def crd(dynamic):
    """Apply the generated CRD to the real apiserver and wait until
    Established — the structural-schema acceptance check no in-repo
    test can provide (VERDICT r1 missing#1)."""
    if SMOKE:
        yield None  # test apiserver speaks EndpointGroupBinding natively
        return
    crd_path = REPO / "config" / "crd"
    applied = []
    for f in sorted(crd_path.glob("*.yaml")):
        applied += dynamic.apply_file(str(f))
    name = applied[0]["metadata"]["name"]
    ref = {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": name},
    }

    def established():
        current = dynamic.get(ref) or {}
        return any(
            c.get("type") == "Established" and c.get("status") == "True"
            for c in current.get("status", {}).get("conditions", [])
        )

    assert wait_until(established), "CRD never became Established"
    yield applied[0]
    # CRD stays installed: later tests and reruns reuse it


def _master_args(server):
    """CLI connection args for subprocess drives."""
    if SMOKE:
        return ["--master", server.url]
    return ["--kubeconfig", os.environ["KUBECONFIG"]]


# ---------------------------------------------------------------------------
# protocol proofs
# ---------------------------------------------------------------------------


class TestCRDLifecycle:
    def test_crd_established(self, crd):
        if SMOKE:
            pytest.skip("test apiserver has no apiextensions")
        assert crd["kind"] == "CustomResourceDefinition"

    def test_crud_status_subresource_and_finalizers(self, client, crd):
        """The full typed round trip through a genuine apiserver:
        create → get → update (optimistic concurrency) → update_status
        (subresource) → finalizer-gated delete."""
        from agac_tpu.apis.endpointgroupbinding import (
            EndpointGroupBinding,
            EndpointGroupBindingSpec,
        )
        from agac_tpu.cluster.objects import ObjectMeta
        from agac_tpu.errors import ConflictError, NotFoundError

        name = "kind-e2e-crud"
        try:
            client.delete("EndpointGroupBinding", "default", name)
        except Exception:
            pass

        binding = EndpointGroupBinding(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=EndpointGroupBindingSpec(
                endpoint_group_arn="arn:aws:globalaccelerator::123:accelerator/a/listener/l/endpoint-group/e",
                weight=32,
            ),
        )
        created = client.create("EndpointGroupBinding", binding)
        assert created.metadata.resource_version

        # optimistic concurrency: a stale update conflicts
        fresh = client.get("EndpointGroupBinding", "default", name)
        stale = client.get("EndpointGroupBinding", "default", name)
        fresh.spec.weight = 64
        client.update("EndpointGroupBinding", fresh)
        stale.spec.weight = 1
        with pytest.raises(ConflictError):
            client.update("EndpointGroupBinding", stale)

        # status subresource: spec edits through /status must not land
        current = client.get("EndpointGroupBinding", "default", name)
        current.status.endpoint_ids = ["arn:lb:1"]
        current.status.observed_generation = current.metadata.generation
        client.update_status("EndpointGroupBinding", current)
        after = client.get("EndpointGroupBinding", "default", name)
        assert after.status.endpoint_ids == ["arn:lb:1"]
        assert after.spec.weight == 64

        # finalizer gate: delete only completes once cleared
        finalized = client.get("EndpointGroupBinding", "default", name)
        finalized.metadata.finalizers = ["operator.h3poteto.dev/binding"]
        client.update("EndpointGroupBinding", finalized)
        client.delete("EndpointGroupBinding", "default", name)
        pending = client.get("EndpointGroupBinding", "default", name)
        assert pending.metadata.deletion_timestamp is not None
        pending.metadata.finalizers = []
        client.update("EndpointGroupBinding", pending)

        def gone():
            try:
                client.get("EndpointGroupBinding", "default", name)
                return False
            except NotFoundError:
                return True

        assert wait_until(gone)


class TestInformerAgainstRealApiserver:
    def test_list_watch_resync_converge(self, client, crd):
        """SharedInformer cache vs direct list — watch priming, ADDED/
        MODIFIED/DELETED dispatch and tombstones, against the real
        watch stream."""
        from agac_tpu.cluster.informer import SharedInformerFactory

        from .fixtures import make_lb_service

        prefix = "kind-e2e-inf"
        for i in range(4):
            try:
                client.delete("Service", "default", f"{prefix}-{i}")
            except Exception:
                pass

        from agac_tpu.controllers.common import unwrap_tombstone

        stop = threading.Event()
        factory = SharedInformerFactory(client, resync_period=2.0)
        informer = factory.informer("Service")
        seen = {"added": set(), "deleted": set()}

        def on_delete(obj):
            unwrapped = unwrap_tombstone(obj)
            if unwrapped is not None:
                seen["deleted"].add(unwrapped.metadata.name)

        informer.add_event_handler(
            on_add=lambda o: seen["added"].add(o.metadata.name),
            on_delete=on_delete,
        )
        factory.start(stop)
        try:
            assert factory.wait_for_cache_sync(stop)
            for i in range(4):
                client.create("Service", make_lb_service(name=f"{prefix}-{i}"))
            lister = informer.lister()
            assert wait_until(
                lambda: len(
                    [s for s in lister.list() if s.metadata.name.startswith(prefix)]
                )
                == 4
            )
            assert wait_until(
                lambda: {f"{prefix}-{i}" for i in range(4)} <= seen["added"]
            )
            client.delete("Service", "default", f"{prefix}-0")
            assert wait_until(lambda: f"{prefix}-0" in seen["deleted"])
        finally:
            stop.set()
            for i in range(1, 4):
                try:
                    client.delete("Service", "default", f"{prefix}-{i}")
                except Exception:
                    pass


class TestControllerProcessAgainstRealApiserver:
    def test_controller_reconciles_and_emits_events(self, server, client, crd):
        """The actual ``controller`` subcommand (leader election,
        informers, all three controllers, fake cloud) run as a
        subprocess against the apiserver: an annotated Service must
        produce a GlobalAcceleratorCreated Event, and annotation
        removal a GlobalAcceleratorDeleted Event — the reference's e2e
        convergence loop with the cloud faked out
        (``local_e2e/e2e_test.go:257-303``)."""
        from .fixtures import NLB_HOSTNAME, NLB_NAME, make_lb_service

        name = "kind-e2e-ctl"
        try:
            client.delete("Service", "default", name)
        except Exception:
            pass

        env = dict(
            os.environ,
            AGAC_CLOUD="fake",
            AGAC_FAKE_LBS=f"{NLB_NAME}={NLB_HOSTNAME}",
            POD_NAMESPACE="default",
        )
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "agac_tpu", "-v", "2", "controller",
                *_master_args(server),
                "--cluster-name", "kind-e2e",
            ],
            cwd=REPO,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            # a real apiserver drops .status on create (and no cloud LB
            # controller runs in kind): set the LB hostname through the
            # status subresource, playing the role of the
            # aws-load-balancer-controller the reference's kops cluster
            # runs (``local_e2e/cluster.yaml:96-101``)
            client.create("Service", make_lb_service(name=name, hostname=None))
            svc = client.get("Service", "default", name)
            from agac_tpu.cluster.objects import LoadBalancerIngress

            svc.status.load_balancer.ingress.append(
                LoadBalancerIngress(hostname=NLB_HOSTNAME)
            )
            client.update_status("Service", svc)

            def event_seen(reason):
                events, _ = client.list("Event", "default")
                return any(
                    e.reason == reason
                    and e.involved_object.name == name
                    for e in events
                )

            assert wait_until(
                lambda: event_seen("GlobalAcceleratorCreated"), timeout=POLL_TIMEOUT
            ), "no GlobalAcceleratorCreated Event (controller logs: %s)" % (
                proc.stdout.read() if proc.poll() is not None else "still running"
            )

            from agac_tpu import apis

            svc = client.get("Service", "default", name)
            del svc.metadata.annotations[
                apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION
            ]
            client.update("Service", svc)
            assert wait_until(
                lambda: event_seen("GlobalAcceleratorDeleted"), timeout=POLL_TIMEOUT
            )

            # leader election used a real Lease on the apiserver
            lease = client.get(
                "Lease", "default", "aws-global-accelerator-controller"
            )
            assert lease.spec.holder_identity
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            try:
                client.delete("Service", "default", name)
            except Exception:
                pass


class TestWebhookThroughRealApiserver:
    def test_arn_immutability_enforced_via_admission(self, client, dynamic, crd):
        """The reference's headline e2e assertions
        (``e2e/e2e_test.go:78-98``): ARN update rejected with
        'Spec.EndpointGroupArn is immutable', weight update allowed —
        through a genuine apiserver's admission chain calling our
        webhook process over TLS."""
        if SMOKE:
            pytest.skip(
                "test apiserver admission is covered by tests/test_webhook_e2e.py; "
                "this test exists for the REAL admission chain"
            )
        url = os.environ.get("E2E_WEBHOOK_URL")
        cert = os.environ.get("E2E_WEBHOOK_CERT")
        key = os.environ.get("E2E_WEBHOOK_KEY")
        ca_bundle = os.environ.get("E2E_WEBHOOK_CA_BUNDLE")
        if not all((url, cert, key, ca_bundle)):
            pytest.skip("E2E_WEBHOOK_* not set (hack/kind-e2e.sh exports them)")

        from agac_tpu.apis.endpointgroupbinding import (
            EndpointGroupBinding,
            EndpointGroupBindingSpec,
        )
        from agac_tpu.cluster.objects import ObjectMeta

        port = url.rsplit(":", 1)[1].split("/")[0]
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "agac_tpu", "webhook",
                "--port", port,
                "--tls-cert-file", cert,
                "--tls-private-key-file", key,
            ],
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        webhook_config = {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "aws-global-accelerator-controller-e2e"},
            "webhooks": [
                {
                    "name": "validating.endpointgroupbindings.operator.h3poteto.dev",
                    "admissionReviewVersions": ["v1"],
                    "clientConfig": {
                        "url": f"{url}/validate-endpointgroupbinding",
                        "caBundle": ca_bundle,
                    },
                    "failurePolicy": "Fail",
                    "rules": [
                        {
                            "apiGroups": ["operator.h3poteto.dev"],
                            "apiVersions": ["v1alpha1"],
                            "operations": ["CREATE", "UPDATE"],
                            "resources": ["endpointgroupbindings"],
                        }
                    ],
                    "sideEffects": "None",
                }
            ],
        }
        name = "kind-e2e-webhook"
        try:
            # webhook must be serving before failurePolicy=Fail gates writes
            def healthy():
                import ssl as ssl_mod
                import urllib.request

                ctx = ssl_mod.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl_mod.CERT_NONE
                try:
                    with urllib.request.urlopen(
                        f"{url}/healthz", context=ctx, timeout=2
                    ) as resp:
                        return resp.status == 200
                except Exception:
                    return False

            assert wait_until(healthy), "webhook process never became healthy"
            dynamic.apply(webhook_config)
            try:
                client.delete("EndpointGroupBinding", "default", name)
            except Exception:
                pass

            binding = EndpointGroupBinding(
                metadata=ObjectMeta(name=name, namespace="default"),
                spec=EndpointGroupBindingSpec(
                    endpoint_group_arn="arn:aws:ga::123:eg/original", weight=10
                ),
            )

            def create_ok():
                try:
                    client.create("EndpointGroupBinding", binding)
                    return True
                except Exception:
                    return False

            assert wait_until(create_ok), "webhook-gated create never succeeded"

            # weight change allowed
            current = client.get("EndpointGroupBinding", "default", name)
            current.spec.weight = 99
            client.update("EndpointGroupBinding", current)

            # ARN change denied with the exact reference message
            current = client.get("EndpointGroupBinding", "default", name)
            current.spec.endpoint_group_arn = "arn:aws:ga::123:eg/changed"
            with pytest.raises(Exception, match="immutable"):
                client.update("EndpointGroupBinding", current)
        finally:
            dynamic.delete(webhook_config)
            try:
                client.delete("EndpointGroupBinding", "default", name)
            except Exception:
                pass
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


class TestApiserverRestartSoak:
    def test_informer_survives_embedded_apiserver_restart(self):
        """Time-compressed smoke rendition of the kind soak below
        (VERDICT r2 next#6): stop the embedded apiserver mid-watch,
        bring it back on the same port with the same backing state
        (etcd's role), and assert the informer relists with no drift —
        so the soak's assertion logic itself is CI-covered, not just
        written.  Self-contained: runs in both tier modes."""
        from agac_tpu.cluster.informer import SharedInformerFactory
        from agac_tpu.cluster.rest import RestClusterClient
        from agac_tpu.cluster.testserver import TestApiServer

        from .fixtures import make_lb_service

        prefix = "smoke-soak"
        first = TestApiServer().start()
        port = int(first.url.rsplit(":", 1)[1])
        local_client = RestClusterClient(first.url)
        stop = threading.Event()
        factory = SharedInformerFactory(local_client, resync_period=0.5)
        informer = factory.informer("Service")
        factory.start(stop)
        second = None
        try:
            assert factory.wait_for_cache_sync(stop)
            local_client.create("Service", make_lb_service(name=f"{prefix}-pre"))
            lister = informer.lister()
            assert wait_until(
                lambda: any(
                    s.metadata.name == f"{prefix}-pre" for s in lister.list()
                )
            )

            first.stop()  # mid-watch outage: streams die, writes fail
            with pytest.raises(Exception):
                local_client.create(
                    "Service", make_lb_service(name=f"{prefix}-down")
                )
            # same state, same address — the kubelet-restarts-the-
            # static-pod moment
            second = TestApiServer(cluster=first.cluster, port=port).start()
            local_client.create("Service", make_lb_service(name=f"{prefix}-post"))
            assert wait_until(
                lambda: {
                    s.metadata.name
                    for s in lister.list()
                    if s.metadata.name.startswith(prefix)
                }
                == {f"{prefix}-pre", f"{prefix}-post"},
                timeout=15,
            ), "informer cache drifted after embedded apiserver restart"
        finally:
            stop.set()
            if second is not None:
                second.stop()

    def test_informer_survives_apiserver_restart(self, client, crd):
        """Kill kube-apiserver inside the kind node (kubelet restarts
        the static pod); the informer must relist and show no drift
        (reference ``local_e2e/e2e_test.go:102-205`` intent)."""
        if SMOKE or os.environ.get("E2E_KIND_SOAK") != "1":
            pytest.skip("soak runs only with E2E_KIND_SOAK=1 on a kind cluster")
        node = os.environ.get("E2E_KIND_NODE", "agac-e2e-control-plane")

        from agac_tpu.cluster.informer import SharedInformerFactory

        from .fixtures import make_lb_service

        prefix = "kind-e2e-soak"
        stop = threading.Event()
        factory = SharedInformerFactory(client, resync_period=2.0)
        informer = factory.informer("Service")
        factory.start(stop)
        try:
            assert factory.wait_for_cache_sync(stop)
            client.create("Service", make_lb_service(name=f"{prefix}-pre"))
            subprocess.run(
                ["docker", "exec", node, "pkill", "-f", "kube-apiserver"],
                check=True,
            )

            def apiserver_back():
                try:
                    client.list("Service", "default")
                    return True
                except Exception:
                    return False

            assert wait_until(apiserver_back, timeout=180, interval=2.0)
            client.create("Service", make_lb_service(name=f"{prefix}-post"))
            lister = informer.lister()
            assert wait_until(
                lambda: {
                    s.metadata.name
                    for s in lister.list()
                    if s.metadata.name.startswith(prefix)
                }
                == {f"{prefix}-pre", f"{prefix}-post"},
                timeout=120,
                interval=2.0,
            ), "informer cache drifted after apiserver restart"
        finally:
            stop.set()
            for suffix in ("pre", "post"):
                try:
                    client.delete("Service", "default", f"{prefix}-{suffix}")
                except Exception:
                    pass
