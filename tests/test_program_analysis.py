"""Whole-program analysis engine tests (ISSUE 12).

Every analysis must catch a seeded fixture violation it claims to
catch — a static auditor that silently misses its target class is
worse than none, because it LOOKS like coverage.  Alongside the
seeded-violation fixtures: the report-schema golden, the baseline
round-trip (add finding -> baseline -> gate green -> remove code ->
stale entry flagged), the runtime cross-check mapping, and the
single-parse-per-file invariant the lint-invariants wall-time fix is
pinned on.
"""

from __future__ import annotations

import json
import textwrap

import pytest

from agac_tpu.analysis import census, confinement, determinism, lockorder  # noqa: F401  (registers rules)
from agac_tpu.analysis.lint import lint_paths
from agac_tpu.analysis.program import (
    Baseline,
    ImportMap,
    ParseCache,
    Program,
    build_report,
    gate_failures,
    run_analyses,
)
from agac_tpu.analysis.program import main as program_main


def build_fixture(tmp_path, files: dict[str, str]) -> Program:
    pkg = tmp_path / "fix"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return Program.build([pkg], ParseCache())


# ---------------------------------------------------------------------------
# lock-order: seeded inversion pair + bare acquire
# ---------------------------------------------------------------------------

INVERSION_SRC = """
    import threading


    class Pair:
        def __init__(self):
            self.first = threading.Lock()
            self.second = threading.Lock()

        def forward(self):
            with self.first:
                with self.second:
                    pass

        def backward(self):
            with self.second:
                self._grab_first()

        def _grab_first(self):
            # the inversion only exists THROUGH the call graph: backward
            # holds `second` while this callee acquires `first`
            with self.first:
                pass
"""


class TestLockOrder:
    def test_seeded_inversion_pair_is_caught(self, tmp_path):
        program = build_fixture(tmp_path, {"pair.py": INVERSION_SRC})
        _, block, findings = lockorder.build_lock_graph(program)
        inversions = [f for f in findings if f.rule == "lock-order-inversion"]
        assert inversions, [f.render() for f in findings]
        assert "fix.pair.Pair.first" in inversions[0].key
        assert "fix.pair.Pair.second" in inversions[0].key
        # both orders appear as static edges
        edges = {tuple(e) for e in block["edges"]}
        assert ("fix.pair.Pair.first", "fix.pair.Pair.second") in edges
        assert ("fix.pair.Pair.second", "fix.pair.Pair.first") in edges

    def test_consistent_order_is_clean(self, tmp_path):
        src = INVERSION_SRC.replace(
            "with self.second:\n                self._grab_first()",
            "with self.first:\n                self._grab_first()",
        ).replace("with self.first:\n                pass", "pass")
        program = build_fixture(tmp_path, {"pair.py": src})
        _, _, findings = lockorder.build_lock_graph(program)
        assert [f for f in findings if f.rule == "lock-order-inversion"] == []

    def test_bare_acquire_without_finally_is_caught(self, tmp_path):
        program = build_fixture(
            tmp_path,
            {
                "bare.py": """
                import threading


                class Holder:
                    def __init__(self):
                        self.mu = threading.Lock()

                    def leaky(self):
                        self.mu.acquire()
                        work = 1
                        self.mu.release()
                        return work

                    def safe(self):
                        self.mu.acquire()
                        try:
                            return 1
                        finally:
                            self.mu.release()
                """
            },
        )
        _, _, findings = lockorder.build_lock_graph(program)
        bare = [f for f in findings if f.rule == "bare-acquire"]
        assert len(bare) == 1, [f.render() for f in findings]
        assert "leaky" in bare[0].key
        assert "safe" not in bare[0].key

    def test_runtime_edge_missing_from_static_graph_is_flagged(self, tmp_path):
        program = build_fixture(tmp_path, {"pair.py": INVERSION_SRC})
        index, block, _ = lockorder.build_lock_graph(program)
        static_edges = {tuple(e) for e in block["edges"]}
        # rename-free fixture: identities double as runtime names via
        # their construction-site prefix — fabricate a name the index
        # cannot map and an edge the graph already covers
        violations, unmapped = lockorder.unmatched_runtime_edges(
            index, static_edges, [("not-a-known-lock", "also-unknown")]
        )
        assert violations == []
        assert unmapped == ["not-a-known-lock"]


# ---------------------------------------------------------------------------
# census: unguarded module global mutated from a thread target
# ---------------------------------------------------------------------------

CENSUS_SRC = """
    import threading

    EVENTS = []


    def worker():
        EVENTS.append("tick")


    def start():
        threading.Thread(target=worker).start()
"""


class TestCensus:
    def test_unguarded_global_mutated_from_thread_target_is_unsafe(self, tmp_path):
        program = build_fixture(tmp_path, {"state.py": CENSUS_SRC})
        block, findings = census.build_census(program)
        entry = next(e for e in block["census"] if e["name"] == "fix.state.EVENTS")
        assert entry["bucket"] == "UNSAFE"
        assert any(f.rule == "shared-state-census" for f in findings)
        # the spawn is discovered through the call graph
        assert "fix.state::worker" in block["thread_roots"]

    def test_lock_guarded_global_is_not_unsafe(self, tmp_path):
        program = build_fixture(
            tmp_path,
            {
                "state.py": """
                import threading

                _lock = threading.Lock()
                EVENTS = []


                def worker():
                    with _lock:
                        EVENTS.append("tick")


                def start():
                    threading.Thread(target=worker).start()
                """
            },
        )
        block, _ = census.build_census(program)
        entry = next(e for e in block["census"] if e["name"] == "fix.state.EVENTS")
        assert entry["bucket"] == "lock-guarded"

    def test_inline_suppression_moves_entry_out_of_unsafe(self, tmp_path):
        src = CENSUS_SRC.replace(
            "EVENTS = []",
            "EVENTS = []  # agac-lint: ignore[shared-state-census] -- test-only sink",
        )
        program = build_fixture(tmp_path, {"state.py": src})
        block, findings = census.build_census(program)
        entry = next(e for e in block["census"] if e["name"] == "fix.state.EVENTS")
        assert entry["bucket"] == "suppressed"
        assert not any(f.rule == "shared-state-census" for f in findings)


# ---------------------------------------------------------------------------
# determinism: set iteration into a trace hash, unseeded random,
# thread spawn outside the clockseam gate
# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_set_iteration_into_trace_hash_is_caught(self, tmp_path):
        program = build_fixture(
            tmp_path,
            {
                "trace.py": """
                import hashlib


                def trace_digest(events):
                    h = hashlib.sha256()
                    for item in {repr(e) for e in events}:
                        h.update(item.encode())
                    return h.hexdigest()


                def sorted_digest(events):
                    h = hashlib.sha256()
                    for item in sorted({repr(e) for e in events}):
                        h.update(item.encode())
                    return h.hexdigest()
                """
            },
        )
        findings, _ = determinism.check_determinism(program)
        keys = {f.key for f in findings if f.rule == "unordered-iteration"}
        assert any("trace_digest" in k for k in keys), keys
        assert not any("sorted_digest" in k for k in keys), keys

    def test_unseeded_random_is_caught(self, tmp_path):
        program = build_fixture(
            tmp_path,
            {
                "jit.py": """
                import random


                def jitter():
                    return random.random()


                def seeded(seed):
                    return random.Random(seed).random()
                """
            },
        )
        findings, _ = determinism.check_determinism(program)
        keys = {f.key for f in findings if f.rule == "unseeded-random"}
        assert any("::jitter" in k for k in keys), keys
        assert not any("::seeded" in k for k in keys), keys

    def test_thread_spawn_outside_clockseam_gate_is_caught(self, tmp_path):
        program = build_fixture(
            tmp_path,
            {
                "spawn.py": """
                import threading

                from somewhere import threads_enabled


                def run():
                    pass


                def ungated():
                    threading.Thread(target=run).start()


                def gated():
                    if threads_enabled():
                        threading.Thread(target=run).start()
                """
            },
        )
        findings, _ = determinism.check_determinism(program)
        keys = {f.key for f in findings if f.rule == "unseamed-thread"}
        assert any("::ungated" in k for k in keys), keys
        assert not any("::gated" in k for k in keys), keys


# ---------------------------------------------------------------------------
# report schema golden + gate
# ---------------------------------------------------------------------------


class TestReportSchema:
    def test_report_schema(self, tmp_path):
        program = build_fixture(tmp_path, {"pair.py": INVERSION_SRC})
        findings, blocks = run_analyses(program)
        report = build_report(program, findings, blocks, Baseline())
        assert report["schema"] == 2
        assert set(report) == {
            "schema", "generated_by", "modules", "parse",
            "analyses", "findings", "baseline", "gate",
        }
        assert set(report["parse"]) >= {"files", "parses", "reparsed"}
        assert report["parse"]["reparsed"] == []
        assert set(report["gate"]) == {
            "new_findings", "unsafe_census", "unportable_stages",
            "stale_baseline", "clean",
        }
        assert set(report["baseline"]) == {"entries", "grandfathered", "stale"}
        assert set(report["analyses"]) == {
            "lock-order", "census", "determinism", "confinement",
        }
        assert set(report["analyses"]["lock-order"]) == {
            "locks", "identities", "edges", "findings",
        }
        assert set(report["analyses"]["census"]) == {
            "census", "buckets", "thread_roots",
        }
        assert set(report["analyses"]["confinement"]) == {
            "stages", "multi_core_candidates", "worker_scope",
            "unseamed_spawners", "picklability", "escapes",
        }
        for f in report["findings"]:
            assert set(f) == {"analysis", "rule", "path", "line", "key", "message"}
        for e in report["analyses"]["census"]["census"]:
            assert set(e) == {
                "name", "kind", "value_type", "path", "line",
                "bucket", "reason", "mutations",
            }
        json.dumps(report)  # machine-readable end to end

    def test_gate_fails_on_new_finding_and_unsafe_census(self, tmp_path):
        program = build_fixture(
            tmp_path, {"pair.py": INVERSION_SRC, "state.py": CENSUS_SRC}
        )
        findings, blocks = run_analyses(program)
        report = build_report(program, findings, blocks, Baseline())
        assert not report["gate"]["clean"]
        failures = gate_failures(report)
        assert any("lock-order-inversion" in f for f in failures)
        assert any("UNSAFE" in f for f in failures)


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


class TestBaseline:
    def test_round_trip_then_stale_when_code_removed(self, tmp_path):
        program = build_fixture(tmp_path, {"pair.py": INVERSION_SRC})
        findings, blocks = run_analyses(program)
        assert findings
        baseline = Baseline(
            {f.key: "grandfathered: pre-existing fixture debt" for f in findings}
        )
        report = build_report(program, findings, blocks, baseline)
        assert report["gate"]["clean"], gate_failures(report)
        assert sorted(report["baseline"]["grandfathered"]) == sorted(
            f.key for f in findings
        )
        # remove the offending code: every baseline entry goes stale
        # and the gate goes red until the entries are dropped
        clean = build_fixture(tmp_path, {"pair.py": "X = 1\n"})
        findings2, blocks2 = run_analyses(clean)
        report2 = build_report(clean, findings2, blocks2, baseline)
        assert report2["baseline"]["stale"] == sorted(baseline.entries)
        assert not report2["gate"]["clean"]
        assert any(
            "matches no current finding" in f for f in gate_failures(report2)
        )

    def test_baseline_keys_are_line_number_stable(self, tmp_path):
        program = build_fixture(tmp_path, {"pair.py": INVERSION_SRC})
        findings, _ = run_analyses(program)
        shifted = build_fixture(
            tmp_path, {"pair.py": "# a comment shifting every line\n" + textwrap.dedent(INVERSION_SRC)}
        )
        findings2, _ = run_analyses(shifted)
        assert {f.key for f in findings} == {f.key for f in findings2}

    def test_save_load_and_reason_mandatory(self, tmp_path):
        path = tmp_path / "baseline.json"
        Baseline({"k::1": "because"}).save(path)
        assert Baseline.load(path).entries == {"k::1": "because"}
        path.write_text(json.dumps({"findings": [{"key": "k::1", "reason": " "}]}))
        with pytest.raises(ValueError):
            Baseline.load(path)

    def test_cli_update_baseline_round_trip(self, tmp_path):
        pkg = tmp_path / "fix"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "pair.py").write_text(textwrap.dedent(INVERSION_SRC))
        report = tmp_path / "report.json"
        baseline = tmp_path / "baseline.json"
        # red without a baseline, green after --update-baseline
        assert program_main(
            [str(pkg), "--report", str(report), "--baseline", str(baseline)]
        ) == 1
        assert program_main(
            [str(pkg), "--report", str(report), "--baseline", str(baseline),
             "--update-baseline"]
        ) == 0
        assert program_main(
            [str(pkg), "--report", str(report), "--baseline", str(baseline)]
        ) == 0
        assert json.loads(report.read_text())["gate"]["clean"]


# ---------------------------------------------------------------------------
# shared parse infra: single parse per file across BOTH runners
# ---------------------------------------------------------------------------


class TestSharedParse:
    def test_single_parse_per_file_across_lint_and_program(self, tmp_path):
        pkg = tmp_path / "fix"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "a.py").write_text("import threading\n\nA = threading.Lock()\n")
        (pkg / "b.py").write_text("def f():\n    return 1\n")
        cache = ParseCache()
        lint_paths([pkg], ci_installed=frozenset(), cache=cache)
        Program.build([pkg], cache)
        assert cache.parse_counts, "nothing parsed?"
        assert set(cache.parse_counts.values()) == {1}, cache.parse_counts

    def test_import_map_is_shared_provenance(self, tmp_path):
        program = build_fixture(
            tmp_path,
            {"m.py": "from time import sleep as pause\nimport threading as th\n"},
        )
        imports = program.modules["fix.m"].imports
        assert isinstance(imports, ImportMap)
        assert imports.resolve("pause") == "time.sleep"
        assert imports.resolve("th") == "threading"
