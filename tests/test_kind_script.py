"""Unit tier for hack/kind-e2e.sh (VERDICT r3 next#4): the script's
logic — preflight, env plumbing, command flow, flag spelling — is
interpreted by a real shell on every ``make test``, up to (and
excluding) the first docker call, via its DRY_RUN mode.  A typo'd
kubectl flag or helm --set key now fails here instead of on the first
real CI run.

Hermetic: every invocation gets a constructed PATH holding only the
tools the scenario grants, so the tests behave identically on a
laptop with docker and in this sandbox without it.
"""

from __future__ import annotations

import os
import pathlib
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "hack" / "kind-e2e.sh"
# resolve the shell before the tests constrain PATH
SH = shutil.which("sh")

# coreutils the script needs even in dry-run (dirname for REPO_ROOT,
# mktemp for WORKDIR, cat for heredocs, rm for cleanup); sh builtins
# (cd, command, printf, trap, pwd) need no shim.  dirname matters:
# without it REPO_ROOT silently collapses to "/" and the dry-run
# certifies a corrupted rendering of the script's paths.
_CORE_TOOLS = ("dirname", "mktemp", "cat", "rm")


@pytest.fixture
def shim_path(tmp_path):
    """A PATH directory holding only core tools; tests grant more."""
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    for tool in _CORE_TOOLS:
        real = shutil.which(tool)
        assert real, f"sandbox lacks {tool}"
        (bin_dir / tool).symlink_to(real)
    return bin_dir


def grant(bin_dir: pathlib.Path, *tools: str) -> None:
    """Grant a tool by shimming it (a no-op script — dry-run never
    executes it; preflight only asks ``command -v``)."""
    for tool in tools:
        shim = bin_dir / tool
        shim.write_text("#!/bin/sh\nexit 0\n")
        shim.chmod(0o755)


def run_script(bin_dir: pathlib.Path, **env_overrides) -> subprocess.CompletedProcess:
    env = {
        "PATH": str(bin_dir),
        "HOME": os.environ.get("HOME", "/root"),
        "TMPDIR": str(bin_dir.parent),
    }
    env.update(env_overrides)
    return subprocess.run(
        [SH, str(SCRIPT)],
        capture_output=True, text=True, env=env, timeout=60,
    )


def test_script_parses():
    subprocess.run([SH, "-n", str(SCRIPT)], check=True)


class TestPreflight:
    def test_reports_all_missing_binaries_at_once(self, shim_path):
        grant(shim_path, "python", "openssl")  # kind/kubectl/docker absent
        result = run_script(shim_path)
        assert result.returncode == 3
        for tool in ("kind", "kubectl", "docker"):
            assert tool in result.stderr
        assert "missing required binaries" in result.stderr

    def test_helm_required_only_for_helm_stage(self, shim_path):
        grant(shim_path, "python", "openssl", "kind", "kubectl", "docker")
        result = run_script(shim_path, HELM_STAGE="1", DRY_RUN="1")
        # dry-run continues, but the preflight names helm
        assert "helm" in result.stderr
        result = run_script(shim_path, HELM_STAGE="1")
        assert result.returncode == 3
        assert "helm" in result.stderr

    def test_dry_run_continues_without_tools(self, shim_path):
        result = run_script(shim_path, DRY_RUN="1")
        assert result.returncode == 0, result.stderr
        assert "preflight (dry-run, continuing)" in result.stderr


class TestDryRunFlow:
    """The full command sequence, in order, with correct env plumbing —
    interpreted by a real shell, no docker needed."""

    @pytest.fixture
    def output(self, shim_path):
        result = run_script(shim_path, DRY_RUN="1", HELM_STAGE="1")
        assert result.returncode == 0, result.stderr
        # the hermetic PATH must not corrupt the rendering (a missing
        # coreutil would print "not found" and collapse REPO_ROOT)
        assert "not found" not in result.stderr, result.stderr
        return result.stdout

    def test_repo_root_paths_render(self, output):
        """Path-carrying commands render the REAL repo root, proving a
        path typo in the script would be visible to this tier."""
        assert f"helm install agac {REPO}/charts/aws-global-accelerator-controller" in output
        assert f"apply -f {REPO}/config/samples/nlb-public-service.yaml" in output
        assert f"docker build -t aws-global-accelerator-controller:e2e {REPO}" in output

    def test_command_sequence_in_order(self, output):
        sequence = [
            "kind create cluster --name agac-e2e --image kindest/node:v1.31.0",
            "kubectl cluster-info --context kind-agac-e2e",
            "docker network inspect kind",
            "openssl req -x509",
            "openssl x509 -req",
            "kind get kubeconfig --name agac-e2e",
            "python -m pytest tests/test_kind_e2e.py -v",
            "docker build -t aws-global-accelerator-controller:e2e",
            "kind load docker-image aws-global-accelerator-controller:e2e",
            "helm install agac",
            "rollout status deployment/aws-global-accelerator-controller",
            "rollout status deployment/aws-global-accelerator-controller-webhook",
            "apply -f",
            "patch service sample-nlb --subresource=status",
            "reason=GlobalAcceleratorCreated,involvedObject.name=sample-nlb",
            "patch endpointgroupbinding sample-binding",
            "expect-denial:",
            "get lease aws-global-accelerator-controller",
            "kind delete cluster --name agac-e2e",
        ]
        position = -1
        for needle in sequence:
            found = output.find(needle, position + 1)
            assert found > position, f"{needle!r} missing or out of order"
            position = found

    def test_pytest_tier_env_plumbing(self, output):
        pytest_line = next(
            line for line in output.splitlines()
            if "python -m pytest tests/test_kind_e2e.py" in line
        )
        for var in (
            "E2E_KIND=1",
            "E2E_KIND_SOAK=0",  # off unless the caller opts in
            "KUBECONFIG=",
            "E2E_WEBHOOK_URL=https://<docker-network-gateway>:18443",
            "E2E_WEBHOOK_CERT=",
            "E2E_WEBHOOK_KEY=",
            "E2E_WEBHOOK_CA_BUNDLE=",
            "E2E_KIND_NODE=agac-e2e-control-plane",
        ):
            assert var in pytest_line, f"{var} not plumbed: {pytest_line}"

    def test_helm_install_set_flags(self, output):
        helm_line = next(
            line for line in output.splitlines() if "helm install agac" in line
        )
        for flag in (
            "--set image.repository=aws-global-accelerator-controller",
            "--set image.tag=e2e",
            "--set image.pullPolicy=Never",
            "--set webhook.enabled=true",
            "--set webhook.certManager.enabled=false",
            "--set webhook.existingCertSecret=agac-e2e-webhook-cert",
            "--set env.AGAC_CLOUD=fake",
        ):
            assert flag in helm_line, f"{flag} missing: {helm_line}"

    def test_denial_probe_expects_immutability_message(self, output):
        assert "immutable" in output  # the webhook's contract, asserted by the probe

    def test_banners_say_dry_run(self, output):
        assert "HELM_STAGE PASSED" in output and "[dry-run: nothing executed]" in output
        assert "kind e2e tier PASSED (k8s 1.31.0) [dry-run: nothing executed]" in output


class TestEnvOverrides:
    def test_version_and_cluster_name_propagate(self, shim_path):
        result = run_script(
            shim_path, DRY_RUN="1", K8S_VERSION="1.29.3", CLUSTER_NAME="custom"
        )
        assert result.returncode == 0, result.stderr
        assert "kindest/node:v1.29.3" in result.stdout
        assert "kind create cluster --name custom" in result.stdout
        assert "E2E_KIND_NODE=custom-control-plane" in result.stdout
        assert "kind delete cluster --name custom" in result.stdout

    def test_keep_cluster_skips_delete(self, shim_path):
        result = run_script(shim_path, DRY_RUN="1", KEEP_CLUSTER="1")
        assert result.returncode == 0, result.stderr
        assert "kind delete cluster" not in result.stdout

    def test_soak_leg_plumbs_to_pytest_tier(self, shim_path):
        """The CI matrix runs E2E_KIND_SOAK=1 HELM_STAGE=1
        (.github/workflows/e2e.yml): under DRY_RUN the exact soak
        plumbing the apiserver-restart tier keys on
        (tests/test_kind_e2e.py:559) must render in the pytest env."""
        result = run_script(shim_path, DRY_RUN="1", E2E_KIND_SOAK="1", HELM_STAGE="1")
        assert result.returncode == 0, result.stderr
        pytest_line = next(
            line for line in result.stdout.splitlines()
            if "python -m pytest tests/test_kind_e2e.py" in line
        )
        assert "E2E_KIND_SOAK=1" in pytest_line
        # and the helm stage still renders downstream of it
        assert "HELM_STAGE PASSED" in result.stdout
