"""Test harness configuration.

The core controller framework has no JAX dependency (the reference is
a Go Kubernetes controller with no tensor workload — SURVEY.md
preamble); only the driver-contract shim ``__graft_entry__.py`` uses
JAX, and its test runs in a subprocess.

Note for this image: the axon TPU plugin is pre-imported via a .pth
hook and overrides ``JAX_PLATFORMS``, so env vars alone cannot select
a virtual CPU mesh — ``jax.config.update('jax_platforms', 'cpu')`` +
``jax.config.update('jax_num_cpu_devices', N)`` before first backend
use is the working mechanism (done inside ``__graft_entry__``).  The
env vars below are kept for environments with a stock jax.
"""

import os
import pathlib
import shutil

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")


# ---------------------------------------------------------------------------
# capture-on-failure (ISSUE 19): the chaos/process e2e tiers arm the
# incident capture for every drill; a red test keeps the recording as
# incident-captures/incident-capture-<test>-*.jsonl — the replayable
# artifact CI uploads, and the seed for a sim regression test.
# ---------------------------------------------------------------------------

KEPT_CAPTURE_DIR = pathlib.Path("incident-captures")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    setattr(item, f"_agac_report_{report.when}", report)


@pytest.fixture
def incident_capture_on_failure(request, tmp_path):
    """Arm every capture entry point for the duration of one test:

    - an in-process wall-clock tap (threads started by the test — the
      chaos fleet drills — record through ``capture.active()``);
    - ``AGAC_CAPTURE_PATH`` with a ``%p`` slot (controller
      subprocesses — the process-kill drills — each write their own
      segment);
    - ``AGAC_SIM_CAPTURE`` (any sim harness the test builds).

    On teardown the recordings are discarded when the test passed and
    kept under ``incident-captures/`` when it failed."""
    from agac_tpu.sim import capture as capture_mod

    capture_dir = tmp_path / "incident-capture"
    capture_dir.mkdir()
    saved_env = {
        name: os.environ.get(name)
        for name in ("AGAC_CAPTURE_PATH", "AGAC_SIM_CAPTURE")
    }
    os.environ["AGAC_CAPTURE_PATH"] = str(capture_dir / "controller-%p.jsonl")
    os.environ["AGAC_SIM_CAPTURE"] = str(capture_dir / "sim.jsonl")
    tap = capture_mod.IncidentCapture(
        str(capture_dir / "live.jsonl"), clock_mode="real", source="test"
    )
    previous = capture_mod.install(tap)
    try:
        yield
    finally:
        capture_mod.install(previous)
        tap.close()
        for name, value in saved_env.items():
            if value is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = value
        failed = any(
            getattr(report, "failed", False)
            for report in (
                getattr(request.node, "_agac_report_setup", None),
                getattr(request.node, "_agac_report_call", None),
            )
        )
        if failed:
            KEPT_CAPTURE_DIR.mkdir(exist_ok=True)
            slug = request.node.name.replace("/", "_").replace("[", "-").strip("]")
            kept = []
            for artifact in sorted(capture_dir.glob("*.jsonl*")):
                target = KEPT_CAPTURE_DIR / f"incident-capture-{slug}-{artifact.name}"
                shutil.copyfile(artifact, target)
                kept.append(str(target))
            if kept:
                print(
                    "incident capture kept (replay: python -m agac_tpu.sim.fuzz"
                    f" --captures {KEPT_CAPTURE_DIR}/): " + ", ".join(kept)
                )
