"""Test harness configuration.

Multi-chip sharding anywhere in the test suite runs on a virtual
8-device CPU mesh, per the driver contract; the core controller
framework itself has no JAX dependency (the reference is a Go
Kubernetes controller with no tensor workload — SURVEY.md preamble).
These env vars must be set before jax is first imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
