"""Test harness configuration.

The core controller framework has no JAX dependency (the reference is
a Go Kubernetes controller with no tensor workload — SURVEY.md
preamble); only the driver-contract shim ``__graft_entry__.py`` uses
JAX, and its test runs in a subprocess.

Note for this image: the axon TPU plugin is pre-imported via a .pth
hook and overrides ``JAX_PLATFORMS``, so env vars alone cannot select
a virtual CPU mesh — ``jax.config.update('jax_platforms', 'cpu')`` +
``jax.config.update('jax_num_cpu_devices', N)`` before first backend
use is the working mechanism (done inside ``__graft_entry__``).  The
env vars below are kept for environments with a stock jax.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
