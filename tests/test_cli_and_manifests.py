"""CLI tests (subcommands, flag defaults, kubeconfig resolution,
webhook SSL validation) and manifest-generation tests (structural
equivalence with the reference's generated config/ tree)."""

import os
import subprocess
import sys

import yaml

from agac_tpu.cmd.root import build_parser, resolve_kubeconfig
from agac_tpu.manifests import (
    crd_manifest,
    rbac_manifest,
    sample_manifests,
    validating_webhook_manifest,
    write_manifests,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "agac_tpu", *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=60,
    )


class TestCLI:
    def test_version_subcommand(self):
        result = run_cli("version")
        assert result.returncode == 0
        assert "Version : 0.1.0" in result.stdout
        assert "Revision:" in result.stdout

    def test_help_lists_subcommands(self):
        result = run_cli("--help")
        for sub in ("controller", "webhook", "version", "manifests"):
            assert sub in result.stdout

    def test_controller_flag_defaults(self):
        args = build_parser().parse_args(["controller"])
        # shipped default is the measured quota-bound operating point
        # (docs/operations.md "Sizing the worker pool"), not the
        # reference's 1
        assert args.workers == 8
        assert args.cluster_name == "default"
        assert args.kubeconfig == ""
        assert args.master == ""
        assert args.queue_qps == 10.0  # client-go default bucket
        assert args.queue_burst == 100

    def test_controller_queue_limit_flags(self):
        args = build_parser().parse_args(
            ["controller", "--queue-qps", "500", "--queue-burst", "1000"]
        )
        assert args.queue_qps == 500.0
        assert args.queue_burst == 1000

    def test_controller_short_flags(self):
        args = build_parser().parse_args(["controller", "-w", "4", "-c", "prod"])
        assert args.workers == 4
        assert args.cluster_name == "prod"

    def test_webhook_requires_tls_files_when_ssl(self):
        result = run_cli("webhook")  # ssl defaults to true, no certs
        assert result.returncode == 2
        assert "--tls-cert-file" in result.stderr

    def test_webhook_flag_defaults(self):
        args = build_parser().parse_args(["webhook"])
        assert args.port == 8443
        assert args.ssl == "true"

    def test_kubeconfig_resolution_order(self, tmp_path, monkeypatch):
        monkeypatch.delenv("KUBECONFIG", raising=False)
        assert resolve_kubeconfig("/explicit/path") == "/explicit/path"
        monkeypatch.setenv("KUBECONFIG", "/from/env")
        assert resolve_kubeconfig("") == "/from/env"
        monkeypatch.delenv("KUBECONFIG")
        fake_home = tmp_path / "home"
        (fake_home / ".kube").mkdir(parents=True)
        (fake_home / ".kube" / "config").write_text("{}")
        monkeypatch.setenv("HOME", str(fake_home))
        assert resolve_kubeconfig("") == str(fake_home / ".kube" / "config")

    def test_controller_without_cluster_errors_cleanly(self, tmp_path):
        env = dict(os.environ, HOME=str(tmp_path), KUBECONFIG="")
        env.pop("KUBERNETES_SERVICE_HOST", None)
        result = subprocess.run(
            [sys.executable, "-m", "agac_tpu", "controller"],
            capture_output=True,
            text=True,
            cwd=REPO,
            env=env,
            timeout=60,
        )
        assert result.returncode == 1
        assert "Error building rest config" in result.stderr

    def test_autoscale_flag_defaults(self):
        args = build_parser().parse_args(["controller"])
        assert args.autoscale is False
        assert args.autoscale_min_shards == 2
        assert args.autoscale_max_shards == 8
        assert args.autoscale_cooldown_out == 120.0
        assert args.autoscale_cooldown_in == 600.0
        assert args.autoscale_interval == 30.0
        assert args.autoscale_observe_only is False

    def test_resize_shards_flags(self):
        args = build_parser().parse_args(["resize-shards", "-n", "4"])
        assert args.shard_count == 4
        assert args.force is False
        assert args.dry_run is False


class TestResizeShardsCLI:
    """run_resize_shards against a stubbed ring lease — the operator
    surface ISSUE 13 pins: plan printout, no-op refusal, --dry-run."""

    @staticmethod
    def make_args(**kw):
        import argparse

        defaults = dict(
            shard_count=4, kubeconfig="/fake", master="",
            force=False, dry_run=False,
        )
        defaults.update(kw)
        return argparse.Namespace(**defaults)

    @staticmethod
    def stub(monkeypatch, status, epoch=7):
        import agac_tpu.cluster.rest as rest
        import agac_tpu.sharding as sharding

        calls = []
        monkeypatch.setattr(rest, "build_client", lambda *a, **k: object())
        monkeypatch.setattr(sharding, "ring_status", lambda *a, **k: status)

        def fake_request(client, n, namespace="kube-system", force=False):
            calls.append((n, force))
            return epoch

        monkeypatch.setattr(sharding, "request_resize", fake_request)
        return calls

    def test_resize_prints_plan_and_requests(self, monkeypatch, capsys):
        from agac_tpu.cmd.root import run_resize_shards

        calls = self.stub(
            monkeypatch,
            {"shard_count": 2, "epoch": 1, "in_flight": False},
        )
        rc = run_resize_shards(self.make_args(shard_count=4))
        out = capsys.readouterr().out
        assert rc == 0
        assert "transition plan 2 -> 4 shards" in out
        assert "of the keyspace moves" in out
        assert "drains to shard(s)" in out
        assert "epoch 7" in out
        assert calls == [(4, False)]

    def test_noop_resize_is_refused(self, monkeypatch, capsys):
        from agac_tpu.cmd.root import run_resize_shards

        calls = self.stub(
            monkeypatch,
            {"shard_count": 4, "epoch": 3, "in_flight": False},
        )
        rc = run_resize_shards(self.make_args(shard_count=4))
        err = capsys.readouterr().err
        assert rc == 1
        assert "already at 4 shards" in err
        assert calls == []

    def test_dry_run_writes_nothing(self, monkeypatch, capsys):
        from agac_tpu.cmd.root import run_resize_shards

        calls = self.stub(
            monkeypatch,
            {"shard_count": 2, "epoch": 1, "in_flight": False},
        )
        rc = run_resize_shards(self.make_args(shard_count=4, dry_run=True))
        out = capsys.readouterr().out
        assert rc == 0
        assert "transition plan 2 -> 4 shards" in out
        assert "dry run: ring lease not written" in out
        assert calls == []

    def test_in_flight_transition_warns_without_force(
        self, monkeypatch, capsys
    ):
        from agac_tpu.cmd.root import run_resize_shards

        self.stub(
            monkeypatch,
            {"shard_count": 2, "epoch": 1, "in_flight": True},
        )
        rc = run_resize_shards(self.make_args(shard_count=4))
        out = capsys.readouterr().out
        assert rc == 0
        assert "still in flight" in out

    def test_refused_request_surfaces_the_reason(self, monkeypatch, capsys):
        import agac_tpu.sharding as sharding
        from agac_tpu.cmd.root import run_resize_shards

        self.stub(
            monkeypatch,
            {"shard_count": 2, "epoch": 1, "in_flight": True},
        )

        def refuse(*a, **k):
            raise RuntimeError("transition in flight; use force=True")

        monkeypatch.setattr(sharding, "request_resize", refuse)
        rc = run_resize_shards(self.make_args(shard_count=4))
        err = capsys.readouterr().err
        assert rc == 1
        assert "resize refused: transition in flight" in err


class TestManifests:
    def test_crd_matches_reference_shape(self):
        crd = crd_manifest()
        assert crd["metadata"]["name"] == "endpointgroupbindings.operator.h3poteto.dev"
        version = crd["spec"]["versions"][0]
        assert version["name"] == "v1alpha1"
        assert version["subresources"] == {"status": {}}
        schema = version["schema"]["openAPIV3Schema"]
        spec_schema = schema["properties"]["spec"]
        assert spec_schema["required"] == ["endpointGroupArn"]
        assert spec_schema["properties"]["clientIPPreservation"]["default"] is False
        assert spec_schema["properties"]["weight"]["nullable"] is True
        status_schema = schema["properties"]["status"]
        assert status_schema["required"] == ["observedGeneration"]
        columns = [c["name"] for c in version["additionalPrinterColumns"]]
        assert columns == ["EndpointGroupArn", "EndpointIds", "Age"]

    def test_webhook_manifest_matches_reference_shape(self):
        hook = validating_webhook_manifest()["webhooks"][0]
        assert hook["failurePolicy"] == "Fail"
        assert hook["clientConfig"]["service"]["path"] == "/validate-endpointgroupbinding"
        assert hook["rules"][0]["operations"] == ["CREATE", "UPDATE"]
        assert hook["rules"][0]["resources"] == ["endpointgroupbindings"]
        assert hook["sideEffects"] == "None"

    def test_rbac_covers_required_access(self):
        rules = rbac_manifest()["rules"]
        by_resource = {}
        for rule in rules:
            for resource in rule["resources"]:
                by_resource.setdefault(resource, set()).update(rule["verbs"])
        assert {"get", "list", "watch"} <= by_resource["services"]
        assert {"get", "list", "watch"} <= by_resource["ingresses"]
        assert "create" in by_resource["events"]
        assert "update" in by_resource["leases"]
        assert "update" in by_resource["endpointgroupbindings"]
        assert "update" in by_resource["endpointgroupbindings/status"]

    def test_write_manifests_round_trip(self, tmp_path):
        written = write_manifests(str(tmp_path))
        assert "crd/operator.h3poteto.dev_endpointgroupbindings.yaml" in written
        assert "webhook/manifests.yaml" in written
        assert "rbac/role.yaml" in written
        for rel in written:
            with open(tmp_path / rel) as fh:
                assert yaml.safe_load(fh)  # valid single-document YAML

    def test_samples_use_annotation_contract(self):
        samples = sample_manifests()
        nlb = samples["nlb-public-service.yaml"]
        annotations = nlb["metadata"]["annotations"]
        assert (
            "aws-global-accelerator-controller.h3poteto.dev/global-accelerator-managed"
            in annotations
        )

    def test_sample_inventory_matches_reference(self):
        # the reference ships 8 samples (config/samples/); every one
        # has an analog here
        assert set(sample_manifests()) == {
            "nlb-public-service.yaml",
            "nlb-internal-service.yaml",
            "nlb-public-ip-service.yaml",
            "service.yaml",
            "alb-public-ingress.yaml",
            "alb-internal-ingress.yaml",
            "deployment.yaml",
            "endpointgroupbinding.yaml",
        }

    def test_iam_policy_covers_driver_calls(self):
        from agac_tpu.manifests.generate import iam_policy

        actions = set(iam_policy()["Statement"][0]["Action"])
        # every AWS API family the driver touches is authorized
        for needed in (
            "elasticloadbalancing:DescribeLoadBalancers",
            "globalaccelerator:CreateAccelerator",
            "globalaccelerator:DeleteEndpointGroup",
            "globalaccelerator:AddEndpoints",
            "globalaccelerator:RemoveEndpoints",
            "route53:ChangeResourceRecordSets",
            "route53:ListHostedZones",
            "route53:ListHostedZonesByName",
            "route53:ListResourceRecordSets",
        ):
            assert needed in actions

    def test_orphan_sweep_spares_user_files(self, tmp_path):
        write_manifests(str(tmp_path))
        overlay = tmp_path / "samples" / "overlays"
        overlay.mkdir()
        keep = tmp_path / "samples" / "README.md"
        keep.write_text("user notes")
        stale = tmp_path / "samples" / "dropped.yaml"
        stale.write_text("kind: Old")
        write_manifests(str(tmp_path))
        assert overlay.is_dir()  # subdirectory untouched
        assert keep.exists()  # non-generated extension untouched
        assert not stale.exists()  # stale generated file reaped

    def test_manifests_cli_writes_tree(self, tmp_path):
        result = run_cli("manifests", "-o", str(tmp_path))
        assert result.returncode == 0
        assert (tmp_path / "rbac" / "role.yaml").exists()


def test_orphan_sweep_extension_is_per_subtree(tmp_path):
    from agac_tpu.manifests.generate import write_manifests

    write_manifests(str(tmp_path))
    user_json = tmp_path / "samples" / "params.json"
    user_json.write_text("{}")
    stale_policy = tmp_path / "iam" / "old.json"
    stale_policy.write_text("{}")
    write_manifests(str(tmp_path))
    assert user_json.exists()  # .json under a yaml subtree is not ours
    assert not stale_policy.exists()  # stale generated json under iam/ reaped


class TestHelmChart:
    CHART = os.path.join(REPO, "charts", "aws-global-accelerator-controller")

    def test_chart_structure(self):
        assert yaml.safe_load(open(os.path.join(self.CHART, "Chart.yaml")))
        values = yaml.safe_load(open(os.path.join(self.CHART, "values.yaml")))
        # values backing every templated knob exist
        assert values["controller"]["queueQps"] == 10
        # disabled by default so the chart installs without cert-manager
        assert values["webhook"]["enabled"] is False
        # the no-cert-manager path (hack/kind-e2e.sh HELM_STAGE) and
        # the extra-env knob it uses must stay declared
        assert values["webhook"]["certManager"]["enabled"] is True
        assert values["webhook"]["existingCertSecret"] == ""
        assert values["webhook"]["caBundle"] == ""
        assert values["env"] == {}
        for name in ("deployment.yaml", "rbac.yaml", "webhook.yaml",
                     "serviceaccount.yaml", "_helpers.tpl", "NOTES.txt"):
            assert os.path.exists(os.path.join(self.CHART, "templates", name))

    def test_chart_crd_in_sync_with_generator(self):
        chart_crd = open(os.path.join(
            self.CHART, "crds", "operator.h3poteto.dev_endpointgroupbindings.yaml"
        )).read()
        assert yaml.safe_load(chart_crd) == crd_manifest()

    def test_templates_have_balanced_actions(self):
        tpl_dir = os.path.join(self.CHART, "templates")
        for name in os.listdir(tpl_dir):
            body = open(os.path.join(tpl_dir, name)).read()
            assert body.count("{{") == body.count("}}"), name
            # every if/range/with/define has a matching end
            import re
            opens = len(re.findall(r"\{\{-?\s*(?:if|range|with|define)\b", body))
            ends = len(re.findall(r"\{\{-?\s*end\b", body))
            assert opens == ends, name

    def test_chart_rbac_matches_generated_role(self):
        # the static rules block in the chart must grant exactly what
        # config/rbac/role.yaml (generated) grants
        body = open(os.path.join(self.CHART, "templates", "rbac.yaml")).read()
        rules_yaml = body.split("rules:", 1)[1].split("---", 1)[0]
        chart_rules = yaml.safe_load("rules:" + rules_yaml)["rules"]

        def grant_set(rules):
            grants = set()
            for rule in rules:
                for group in rule["apiGroups"]:
                    for resource in rule["resources"]:
                        for verb in rule["verbs"]:
                            grants.add((group, resource, verb))
            return grants

        assert grant_set(chart_rules) == grant_set(rbac_manifest()["rules"])


    def test_chart_webhook_matches_generated_config(self):
        # name and rules of the templated ValidatingWebhookConfiguration
        # must match the generator's (same validation, two deploy paths)
        body = open(os.path.join(self.CHART, "templates", "webhook.yaml")).read()
        gen_hook = validating_webhook_manifest()["webhooks"][0]
        assert f"- name: {gen_hook['name']}" in body
        assert f"path: {gen_hook['clientConfig']['service']['path']}" in body
        for resource in gen_hook["rules"][0]["resources"]:
            assert resource in body
        assert "failurePolicy: " + gen_hook["failurePolicy"] in body
