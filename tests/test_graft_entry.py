"""Driver-contract smoke test: __graft_entry__ must compile single-chip
and dry-run the multi-chip sharding on a virtual 8-device CPU mesh.
Run in a subprocess because platform selection must happen before the
first backend initialization."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_graft_entry_self_test():
    result = subprocess.run(
        [sys.executable, "__graft_entry__.py"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=300,
    )
    assert result.returncode == 0, result.stderr
    assert "dryrun_multichip OK: mesh=(4 data x 2 model)" in result.stdout
    assert "entry() forward: (32, 64)" in result.stdout
