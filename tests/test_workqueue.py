"""Workqueue semantics tests: dedup, in-flight coalescing, delayed and
rate-limited adds, shutdown — the client-go contract the reference's
controllers rely on (SURVEY.md §2 row 5).

Timing-dependent behavior (delayed delivery ordering, token-bucket
refill) is driven by a FakeClock through the injectable ``clock``
seams instead of sleeping real wall time: the limiter/queue tests
that used to burn ~0.4 s of sleeps now run in milliseconds and assert
EXACT delivery times instead of sloppy real-clock bounds."""

import threading
import time

import pytest

from agac_tpu.reconcile.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    MaxOfRateLimiter,
    RateLimitingQueue,
)


class FakeClock:
    """A manually advanced monotonic clock.  ``advance`` optionally
    kicks a queue's delay waker — a fake clock cannot make a real
    ``Condition.wait`` return early, so tests poke the waker after
    moving time (the ``kick_delays`` seam)."""

    def __init__(self, start: float = 1000.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float, queue: RateLimitingQueue | None = None) -> None:
        self.now += dt
        if queue is not None:
            queue.kick_delays()


@pytest.fixture
def queue():
    q = RateLimitingQueue(name="test")
    yield q
    q.shutdown()


def test_fifo_order(queue):
    queue.add("a")
    queue.add("b")
    assert queue.get() == ("a", False)
    assert queue.get() == ("b", False)


def test_duplicate_adds_coalesce(queue):
    queue.add("a")
    queue.add("a")
    assert len(queue) == 1
    item, _ = queue.get()
    queue.done(item)
    assert len(queue) == 0


def test_add_while_processing_requeues_on_done(queue):
    queue.add("a")
    item, _ = queue.get()
    queue.add("a")  # arrives while "a" is being processed
    assert len(queue) == 0  # not handed out concurrently
    queue.done(item)
    assert len(queue) == 1  # re-queued after done
    assert queue.get() == ("a", False)


def test_get_blocks_until_add(queue):
    results = []

    def worker():
        results.append(queue.get())

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    queue.add("x")
    t.join(timeout=2)
    assert results == [("x", False)]


def test_get_timeout_returns_none_not_shutdown(queue):
    assert queue.get(timeout=0.01) == (None, False)


def test_add_after_delivers_on_clock_advance():
    """Fake-clock conversion of the old real-sleep delivers-later test:
    the delay boundary is asserted EXACTLY (9.9 s: not yet; 10 s:
    delivered) with no wall-time sleeping."""
    clock = FakeClock()
    queue = RateLimitingQueue(name="fake-clock", clock=clock)
    try:
        queue.add_after("later", 10.0)
        assert len(queue) == 0
        clock.advance(9.9, queue)
        assert queue.get(timeout=0.05) == (None, False)  # not ready yet
        clock.advance(0.1, queue)
        assert queue.get(timeout=2) == ("later", False)
    finally:
        queue.shutdown()


def test_add_after_zero_is_immediate(queue):
    queue.add_after("now", 0)
    assert queue.get(timeout=1) == ("now", False)


def test_add_after_ordering():
    """Fake-clock conversion of the old 0.15 s-sleep ordering test:
    heap order is by ready time, not insertion order."""
    clock = FakeClock()
    queue = RateLimitingQueue(name="fake-clock", clock=clock)
    try:
        queue.add_after("slow", 15.0)
        queue.add_after("fast", 2.0)
        clock.advance(2.0, queue)
        assert queue.get(timeout=2)[0] == "fast"
        assert len(queue) == 0
        clock.advance(13.0, queue)
        assert queue.get(timeout=2)[0] == "slow"
    finally:
        queue.shutdown()


def test_shutdown_unblocks_get(queue):
    results = []

    def worker():
        results.append(queue.get())

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    queue.shutdown()
    t.join(timeout=2)
    assert results == [(None, True)]
    assert queue.shutting_down()


def test_add_after_shutdown_is_noop():
    clock = FakeClock()
    queue = RateLimitingQueue(name="fake-clock", clock=clock)
    queue.shutdown()
    queue.add("x")
    queue.add_after("y", 0.01)
    clock.advance(1.0, queue)
    assert len(queue) == 0


def test_persistent_failure_backoff_never_overflows():
    limiter = ItemExponentialFailureRateLimiter(base_delay=0.005, max_delay=1000.0)
    limiter._failures["stuck"] = 5000  # simulate ~weeks of failures
    assert limiter.when("stuck") == 1000.0


def test_rate_limited_backoff_grows_and_forget_resets():
    limiter = ItemExponentialFailureRateLimiter(base_delay=0.005, max_delay=1000.0)
    assert limiter.when("a") == pytest.approx(0.005)
    assert limiter.when("a") == pytest.approx(0.01)
    assert limiter.when("a") == pytest.approx(0.02)
    assert limiter.num_requeues("a") == 3
    # independent per item
    assert limiter.when("b") == pytest.approx(0.005)
    limiter.forget("a")
    assert limiter.when("a") == pytest.approx(0.005)


def test_exponential_limiter_caps():
    limiter = ItemExponentialFailureRateLimiter(base_delay=0.005, max_delay=0.02)
    for _ in range(10):
        delay = limiter.when("a")
    assert delay == pytest.approx(0.02)


def test_bucket_limiter_burst_then_throttle():
    limiter = BucketRateLimiter(qps=10.0, burst=2)
    assert limiter.when("x") == 0.0
    assert limiter.when("x") == 0.0
    assert limiter.when("x") > 0.0  # burst exhausted


def test_bucket_refill_with_fake_clock():
    """The injected clock drives refill deterministically: exact
    reservation delays and exact recovery after simulated idle time —
    previously only assertable by sleeping real wall seconds."""
    clock = FakeClock()
    limiter = BucketRateLimiter(qps=10.0, burst=2, clock=clock)
    assert limiter.when("a") == 0.0
    assert limiter.when("a") == 0.0
    # bucket empty: each reservation queues exactly 0.1 s behind the last
    assert limiter.when("a") == pytest.approx(0.1)
    assert limiter.when("a") == pytest.approx(0.2)
    # 1 s of simulated idle refills to the burst cap (not beyond):
    # 2 tokens deep in debt + 10 tokens refilled, capped at burst=2
    clock.advance(1.0)
    assert limiter.when("a") == 0.0
    assert limiter.when("a") == 0.0
    assert limiter.when("a") == pytest.approx(0.1)


def test_controller_rate_limiter_bucket_refills_on_fake_clock():
    """The clock threads through controller_rate_limiter to its
    bucket: after simulated idle, the bucket contributes nothing and
    only the per-item exponential backoff remains."""
    from agac_tpu.reconcile import controller_rate_limiter

    clock = FakeClock()
    limiter = controller_rate_limiter(qps=1.0, burst=1, clock=clock)
    assert limiter.when("x") == pytest.approx(0.005)  # burst token + 5 ms base
    # burst spent: the 1 qps bucket dominates the 10 ms exponential
    assert limiter.when("x") == pytest.approx(1.0)
    clock.advance(10.0)
    # refilled: the exponential (now 2^2 * 5 ms) is the only delay
    assert limiter.when("x") == pytest.approx(0.02)


def test_max_of_rate_limiter():
    fast = ItemExponentialFailureRateLimiter(base_delay=0.001, max_delay=1)
    slow = ItemExponentialFailureRateLimiter(base_delay=0.1, max_delay=1)
    combined = MaxOfRateLimiter(fast, slow)
    assert combined.when("a") == pytest.approx(0.1)
    assert combined.num_requeues("a") == 1
    combined.forget("a")
    assert combined.num_requeues("a") == 0


def test_add_rate_limited_delivers(queue):
    queue.add_rate_limited("item")
    item, shutdown = queue.get(timeout=2)
    assert (item, shutdown) == ("item", False)


def test_controller_rate_limiter_tunable_bucket():
    """controller_rate_limiter(qps, burst) keeps the client-go shape
    (per-item exponential + overall bucket) but with a tunable bucket
    — the queue_qps/queue_burst production knob."""
    from agac_tpu.reconcile import controller_rate_limiter

    limiter = controller_rate_limiter(qps=1000.0, burst=3)
    # within burst the bucket contributes nothing; only the 5 ms
    # exponential base applies (client-go parity)
    assert limiter.when("a") == 0.005
    assert limiter.when("b") == 0.005
    assert limiter.when("c") == 0.005
    # per-item exponential still doubles on repeated failures
    assert limiter.when("a") == 0.01
    # a slow bucket dominates once the burst is spent
    slow = controller_rate_limiter(qps=1.0, burst=1)
    assert slow.when("x") == 0.005  # burst token
    assert slow.when("y") > 0.5  # throttled at ~1/qps


def test_controller_rate_limiter_qps_zero_disables_bucket():
    """--queue-qps 0 means unlimited: no ZeroDivisionError, per-item
    exponential backoff still applies."""
    from agac_tpu.reconcile import controller_rate_limiter

    limiter = controller_rate_limiter(qps=0.0, burst=1)
    for item in range(50):
        assert limiter.when(item) == 0.005  # no bucket throttling
    assert limiter.when(0) == 0.01  # exponential still present
