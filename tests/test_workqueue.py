"""Workqueue semantics tests: dedup, in-flight coalescing, delayed and
rate-limited adds, shutdown — the client-go contract the reference's
controllers rely on (SURVEY.md §2 row 5)."""

import threading
import time

import pytest

from agac_tpu.reconcile.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    MaxOfRateLimiter,
    RateLimitingQueue,
)


@pytest.fixture
def queue():
    q = RateLimitingQueue(name="test")
    yield q
    q.shutdown()


def test_fifo_order(queue):
    queue.add("a")
    queue.add("b")
    assert queue.get() == ("a", False)
    assert queue.get() == ("b", False)


def test_duplicate_adds_coalesce(queue):
    queue.add("a")
    queue.add("a")
    assert len(queue) == 1
    item, _ = queue.get()
    queue.done(item)
    assert len(queue) == 0


def test_add_while_processing_requeues_on_done(queue):
    queue.add("a")
    item, _ = queue.get()
    queue.add("a")  # arrives while "a" is being processed
    assert len(queue) == 0  # not handed out concurrently
    queue.done(item)
    assert len(queue) == 1  # re-queued after done
    assert queue.get() == ("a", False)


def test_get_blocks_until_add(queue):
    results = []

    def worker():
        results.append(queue.get())

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    queue.add("x")
    t.join(timeout=2)
    assert results == [("x", False)]


def test_get_timeout_returns_none_not_shutdown(queue):
    assert queue.get(timeout=0.01) == (None, False)


def test_add_after_delivers_later(queue):
    start = time.monotonic()
    queue.add_after("later", 0.1)
    assert queue.get(timeout=0.02) == (None, False)
    item, shutdown = queue.get(timeout=2)
    assert (item, shutdown) == ("later", False)
    assert time.monotonic() - start >= 0.09


def test_add_after_zero_is_immediate(queue):
    queue.add_after("now", 0)
    assert queue.get(timeout=1) == ("now", False)


def test_add_after_ordering(queue):
    queue.add_after("slow", 0.15)
    queue.add_after("fast", 0.02)
    assert queue.get(timeout=2)[0] == "fast"
    assert queue.get(timeout=2)[0] == "slow"


def test_shutdown_unblocks_get(queue):
    results = []

    def worker():
        results.append(queue.get())

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    queue.shutdown()
    t.join(timeout=2)
    assert results == [(None, True)]
    assert queue.shutting_down()


def test_add_after_shutdown_is_noop(queue):
    queue.shutdown()
    queue.add("x")
    queue.add_after("y", 0.01)
    time.sleep(0.05)
    assert len(queue) == 0


def test_persistent_failure_backoff_never_overflows():
    limiter = ItemExponentialFailureRateLimiter(base_delay=0.005, max_delay=1000.0)
    limiter._failures["stuck"] = 5000  # simulate ~weeks of failures
    assert limiter.when("stuck") == 1000.0


def test_rate_limited_backoff_grows_and_forget_resets():
    limiter = ItemExponentialFailureRateLimiter(base_delay=0.005, max_delay=1000.0)
    assert limiter.when("a") == pytest.approx(0.005)
    assert limiter.when("a") == pytest.approx(0.01)
    assert limiter.when("a") == pytest.approx(0.02)
    assert limiter.num_requeues("a") == 3
    # independent per item
    assert limiter.when("b") == pytest.approx(0.005)
    limiter.forget("a")
    assert limiter.when("a") == pytest.approx(0.005)


def test_exponential_limiter_caps():
    limiter = ItemExponentialFailureRateLimiter(base_delay=0.005, max_delay=0.02)
    for _ in range(10):
        delay = limiter.when("a")
    assert delay == pytest.approx(0.02)


def test_bucket_limiter_burst_then_throttle():
    limiter = BucketRateLimiter(qps=10.0, burst=2)
    assert limiter.when("x") == 0.0
    assert limiter.when("x") == 0.0
    assert limiter.when("x") > 0.0  # burst exhausted


def test_max_of_rate_limiter():
    fast = ItemExponentialFailureRateLimiter(base_delay=0.001, max_delay=1)
    slow = ItemExponentialFailureRateLimiter(base_delay=0.1, max_delay=1)
    combined = MaxOfRateLimiter(fast, slow)
    assert combined.when("a") == pytest.approx(0.1)
    assert combined.num_requeues("a") == 1
    combined.forget("a")
    assert combined.num_requeues("a") == 0


def test_add_rate_limited_delivers(queue):
    queue.add_rate_limited("item")
    item, shutdown = queue.get(timeout=2)
    assert (item, shutdown) == ("item", False)


def test_controller_rate_limiter_tunable_bucket():
    """controller_rate_limiter(qps, burst) keeps the client-go shape
    (per-item exponential + overall bucket) but with a tunable bucket
    — the queue_qps/queue_burst production knob."""
    from agac_tpu.reconcile import controller_rate_limiter

    limiter = controller_rate_limiter(qps=1000.0, burst=3)
    # within burst the bucket contributes nothing; only the 5 ms
    # exponential base applies (client-go parity)
    assert limiter.when("a") == 0.005
    assert limiter.when("b") == 0.005
    assert limiter.when("c") == 0.005
    # per-item exponential still doubles on repeated failures
    assert limiter.when("a") == 0.01
    # a slow bucket dominates once the burst is spent
    slow = controller_rate_limiter(qps=1.0, burst=1)
    assert slow.when("x") == 0.005  # burst token
    assert slow.when("y") > 0.5  # throttled at ~1/qps


def test_controller_rate_limiter_qps_zero_disables_bucket():
    """--queue-qps 0 means unlimited: no ZeroDivisionError, per-item
    exponential backoff still applies."""
    from agac_tpu.reconcile import controller_rate_limiter

    limiter = controller_rate_limiter(qps=0.0, burst=1)
    for item in range(50):
        assert limiter.when(item) == 0.005  # no bucket throttling
    assert limiter.when(0) == 0.01  # exponential still present
