"""Discovery-cache tests: hit/miss accounting, TTL expiry,
invalidation on every mutating driver path, and correctness of the
cached ensure flow (a reconcile never acts on its own stale write)."""

import dataclasses
import pytest

from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cloudprovider.aws.cache import DiscoveryCache

from .fixtures import NLB_HOSTNAME, NLB_NAME, NLB_REGION, make_lb_service


@pytest.fixture
def backend():
    fake = FakeAWSBackend()
    fake.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
    return fake


def make_driver(backend, cache):
    return AWSDriver(
        backend, backend, backend,
        poll_interval=0.001, poll_timeout=1.0, discovery_cache=cache,
    )


def ensure(driver, svc):
    return driver.ensure_global_accelerator_for_service(
        svc, svc.status.load_balancer.ingress[0], "default", NLB_NAME, NLB_REGION
    )


def test_ttl_and_explicit_clock():
    now = [0.0]
    cache = DiscoveryCache(ttl=5.0, clock=lambda: now[0])
    loads = []
    loader = lambda: loads.append(1) or []
    cache.get(loader)
    cache.get(loader)
    assert len(loads) == 1 and cache.hits == 1 and cache.misses == 1
    now[0] = 6.0  # expired
    cache.get(loader)
    assert len(loads) == 2


def test_cached_discovery_reduces_aws_calls(backend):
    cache = DiscoveryCache(ttl=60.0)
    driver = make_driver(backend, cache)
    svc = make_lb_service()
    ensure(driver, svc)  # create pass (invalidates at creation)
    ensure(driver, svc)  # converged pass: discovery from cache? no — create invalidated
    before = sum(1 for c in backend.calls if c[0] == "ListAccelerators")
    for _ in range(10):
        ensure(driver, svc)  # steady state, no mutations
    after = sum(1 for c in backend.calls if c[0] == "ListAccelerators")
    assert after - before <= 1  # at most one refill for 10 reconciles


def test_write_invalidates_own_cache(backend):
    """Create must be visible to the immediately following discovery,
    or every second reconcile would create a duplicate accelerator."""
    cache = DiscoveryCache(ttl=60.0)
    driver = make_driver(backend, cache)
    svc = make_lb_service()
    # warm the cache with the empty state
    assert driver.list_global_accelerator_by_resource("default", "service", "default", "web") == []
    arn1, created1, _ = ensure(driver, svc)
    arn2, created2, _ = ensure(driver, svc)
    assert created1 and not created2
    assert arn1 == arn2
    assert len(backend.all_accelerator_arns()) == 1


def test_cleanup_invalidates(backend):
    cache = DiscoveryCache(ttl=60.0)
    driver = make_driver(backend, cache)
    svc = make_lb_service()
    arn, _, _ = ensure(driver, svc)
    driver.cleanup_global_accelerator(arn)
    assert driver.list_global_accelerator_by_resource("default", "service", "default", "web") == []


def test_shared_cache_across_drivers(backend):
    """The factory shares one cache across per-reconcile drivers."""
    cache = DiscoveryCache(ttl=60.0)
    svc = make_lb_service()
    ensure(make_driver(backend, cache), svc)
    before = sum(1 for c in backend.calls if c[0] == "ListAccelerators")
    for _ in range(5):
        ensure(make_driver(backend, cache), svc)  # new driver each time
    after = sum(1 for c in backend.calls if c[0] == "ListAccelerators")
    assert after - before <= 1


def test_snapshot_isolation(backend):
    """Callers must not be able to corrupt the cached snapshot: entries
    are shared (no per-read copy), so Accelerator is frozen and any
    mutation attempt raises instead of silently poisoning the cache."""
    cache = DiscoveryCache(ttl=60.0)
    driver = make_driver(backend, cache)
    svc = make_lb_service()
    ensure(driver, svc)
    found = driver.list_global_accelerator_by_resource("default", "service", "default", "web")
    with pytest.raises(dataclasses.FrozenInstanceError):
        found[0].name = "mutated-by-caller"
    again = driver.list_global_accelerator_by_resource("default", "service", "default", "web")
    assert again[0].name == "service-default-web"


def test_create_folds_into_snapshot_without_rescan(backend):
    """A create upserts into the warm snapshot: no full tag rescan,
    and the creator immediately sees its own write."""
    cache = DiscoveryCache(ttl=60.0)
    driver = make_driver(backend, cache)
    svc = make_lb_service()
    ensure(driver, svc)  # warms the cache, then creates (upsert)
    scans_before = sum(1 for c in backend.calls if c[0] == "ListAccelerators")
    found = driver.list_global_accelerator_by_resource(
        "default", "service", "default", "web"
    )
    assert len(found) == 1  # own write visible through the cache
    scans_after = sum(1 for c in backend.calls if c[0] == "ListAccelerators")
    assert scans_after == scans_before  # served from the upserted snapshot


def test_creation_storm_is_linear_in_tag_scans(backend):
    """N creates against a warm cache cost O(1) full scans, not O(N)
    (the blanket-invalidate behavior this replaced)."""
    cache = DiscoveryCache(ttl=60.0)
    for i in range(8):
        backend.add_load_balancer(f"storm{i:02d}", NLB_REGION,
                                  f"storm{i:02d}-0123456789abcdef.elb.us-west-2.amazonaws.com")
    for i in range(8):
        svc = make_lb_service(name=f"storm{i:02d}")
        svc.status.load_balancer.ingress[0].hostname = (
            f"storm{i:02d}-0123456789abcdef.elb.us-west-2.amazonaws.com"
        )
        driver = make_driver(backend, cache)
        driver.ensure_global_accelerator_for_service(
            svc, svc.status.load_balancer.ingress[0], "default",
            f"storm{i:02d}", NLB_REGION,
        )
    scans = sum(1 for c in backend.calls if c[0] == "ListAccelerators")
    assert scans <= 2  # one warming load (+ at most one re-load)


def test_delete_removes_from_snapshot(backend):
    cache = DiscoveryCache(ttl=60.0)
    driver = make_driver(backend, cache)
    svc = make_lb_service()
    arn, _, _ = ensure(driver, svc)
    driver.list_global_accelerator_by_resource("default", "service", "default", "web")
    driver.cleanup_global_accelerator(arn)
    assert (
        driver.list_global_accelerator_by_resource("default", "service", "default", "web")
        == []
    )


def test_upsert_blocks_stale_inflight_load():
    """A loader that began before a write must not be stored over it."""
    from agac_tpu.cloudprovider.aws.types import Accelerator

    cache = DiscoveryCache(ttl=60.0)
    acc = Accelerator(
        accelerator_arn="arn:new", name="n", enabled=True,
        status="DEPLOYED", dns_name="d",
    )

    def stale_loader():
        # write lands while the load is in flight
        cache.upsert(acc, [])
        return []  # the stale (pre-write) view

    cache.get(stale_loader)
    # a fresh get must not see the stale stored snapshot: either it
    # reloads or serves a snapshot containing the upserted entry
    snapshot = cache.get(lambda: [(acc, [])])
    assert any(a.accelerator_arn == "arn:new" for a, _ in snapshot)


def test_single_flight_load():
    """Concurrent missers issue ONE scan; the rest wait for it
    (storm behavior: 32 workers must not run 32 O(N) scans)."""
    import threading

    cache = DiscoveryCache(ttl=60.0)
    started = threading.Event()
    release = threading.Event()
    loads = []

    def slow_loader():
        loads.append(1)
        started.set()
        release.wait(5.0)
        return []

    results = []
    threads = [
        threading.Thread(target=lambda: results.append(cache.get(slow_loader)))
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    assert started.wait(5.0)
    release.set()
    for t in threads:
        t.join(5.0)
    assert len(loads) == 1  # one scan served all eight workers
    assert len(results) == 8
    assert cache.misses == 1 and cache.hits == 7


def test_journal_merges_storm_writes_into_loaded_snapshot():
    """A write during an in-flight load is folded into the stored
    snapshot (not discarded): the next get() is a HIT that sees the
    write — creation storms stay O(N), not O(N^2)."""
    from agac_tpu.cloudprovider.aws.types import Accelerator

    cache = DiscoveryCache(ttl=60.0)
    acc = Accelerator(
        accelerator_arn="arn:during-load", name="n", enabled=True,
        status="DEPLOYED", dns_name="d",
    )

    def loader_with_concurrent_write():
        cache.upsert(acc, [])  # write lands mid-scan
        return []  # the scan's (stale) view

    merged = cache.get(loader_with_concurrent_write)
    assert any(a.accelerator_arn == "arn:during-load" for a, _ in merged)
    hits_before = cache.hits
    again = cache.get(lambda: pytest.fail("must be served from cache"))
    assert cache.hits == hits_before + 1
    assert any(a.accelerator_arn == "arn:during-load" for a, _ in again)


def test_invalidate_during_load_prevents_store():
    """invalidate (external change) mid-load: the result is returned
    but NOT stored — the next get() rescans."""
    cache = DiscoveryCache(ttl=60.0)

    def loader_with_concurrent_invalidate():
        cache.invalidate()
        return []

    cache.get(loader_with_concurrent_invalidate)
    loads = []
    cache.get(lambda: loads.append(1) or [])
    assert loads == [1]  # rescan, not a hit


def test_failed_load_releases_single_flight():
    """A loader exception must not wedge the single-flight latch."""
    cache = DiscoveryCache(ttl=60.0)
    with pytest.raises(RuntimeError):
        cache.get(lambda: (_ for _ in ()).throw(RuntimeError("scan failed")))
    loads = []
    cache.get(lambda: loads.append(1) or [])  # next load proceeds
    assert loads == [1]


class TestHostedZoneCache:
    """The zone-snapshot cache: get_hosted_zone's parent-domain walk
    runs in memory against one ListHostedZones drain per TTL."""

    def test_walk_served_from_one_snapshot(self, backend):
        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache

        backend.add_hosted_zone("example.com")
        backend.add_hosted_zone("apps.example.com")
        cache = HostedZoneCache(ttl=60.0)
        driver = AWSDriver(backend, backend, backend, zone_cache=cache)
        z1 = driver.get_hosted_zone("www.apps.example.com")
        z2 = driver.get_hosted_zone("api.example.com")
        z3 = driver.get_hosted_zone("deep.sub.apps.example.com")
        assert z1.name == "apps.example.com."
        assert z2.name == "example.com."
        assert z3.name == "apps.example.com."
        # exactly one snapshot load served all three walks
        assert cache.misses == 1 and cache.hits == 2

    def test_snapshot_miss_falls_back_to_live_walk(self, backend):
        """A zone created after the snapshot is still found (the live
        walk is the source of truth) and the stale snapshot drops."""
        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache

        backend.add_hosted_zone("example.com")
        cache = HostedZoneCache(ttl=60.0)
        driver = AWSDriver(backend, backend, backend, zone_cache=cache)
        driver.get_hosted_zone("www.example.com")  # warms the snapshot
        backend.add_hosted_zone("newzone.net")  # created moments later
        zone = driver.get_hosted_zone("api.newzone.net")
        assert zone.name == "newzone.net."
        # the stale snapshot was dropped: the next walk re-reads
        misses_before = cache.misses
        driver.get_hosted_zone("www.example.com")
        assert cache.misses == misses_before + 1

    def test_absent_zone_raises_like_uncached(self, backend):
        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache
        from agac_tpu.cloudprovider.aws.errors import AWSAPIError

        backend.add_hosted_zone("example.com")
        cache = HostedZoneCache(ttl=60.0)
        driver = AWSDriver(backend, backend, backend, zone_cache=cache)
        with pytest.raises(AWSAPIError, match="NoSuchHostedZone"):
            driver.get_hosted_zone("www.unrelated.org")

    def test_single_flight_zone_load(self):
        import threading

        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache

        cache = HostedZoneCache(ttl=60.0)
        started, release, loads = threading.Event(), threading.Event(), []

        def slow_loader():
            loads.append(1)
            started.set()
            release.wait(5.0)
            return []

        threads = [
            threading.Thread(target=lambda: cache.zones(slow_loader))
            for _ in range(6)
        ]
        for t in threads:
            t.start()
        assert started.wait(5.0)
        release.set()
        for t in threads:
            t.join(5.0)
        assert len(loads) == 1

    def test_cleanup_scan_uses_snapshot(self, backend):
        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache

        backend.add_hosted_zone("example.com")
        cache = HostedZoneCache(ttl=60.0)
        driver = make_driver(backend, None)
        driver._zone_cache = cache
        driver.get_hosted_zone("www.example.com")  # warm
        before = sum(1 for c in backend.calls if c[0] == "ChangeResourceRecordSets")
        driver.cleanup_record_set("default", "service", "default", "gone")
        # the cleanup's all-zones scan came from the snapshot: zero
        # fresh ListHostedZones beyond the warming load
        assert cache.misses == 1 and cache.hits >= 1
        # and a cleanup for an owner with no records mutates nothing
        after = sum(1 for c in backend.calls if c[0] == "ChangeResourceRecordSets")
        assert after == before

    def test_cleanup_invalidates_on_out_of_band_zone_delete(self, backend):
        """A snapshot zone deleted out-of-band fails the cleanup scan
        with NoSuchHostedZone ONCE; the snapshot is dropped so the
        retry re-reads instead of re-failing for the rest of the TTL
        (same repair rule as the ensure path)."""
        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache
        from agac_tpu.cloudprovider.aws.errors import AWSAPIError

        zone = backend.add_hosted_zone("example.com")
        cache = HostedZoneCache(ttl=600.0)
        driver = make_driver(backend, None)
        driver._zone_cache = cache
        driver.get_hosted_zone("www.example.com")  # warm the snapshot
        # out-of-band: the zone disappears behind the controller
        with backend._lock:
            del backend._zones[zone.id]
            del backend._records[zone.id]
        with pytest.raises(AWSAPIError, match="NoSuchHostedZone"):
            driver.cleanup_record_set("default", "service", "default", "web")
        # the failure dropped the snapshot: the retry reloads and,
        # with the zone truly gone, scans nothing and succeeds
        misses_before = cache.misses
        driver.cleanup_record_set("default", "service", "default", "web")
        assert cache.misses == misses_before + 1

    def test_misconfigured_hostname_keeps_snapshot_warm(self, backend):
        """A Service whose route53-hostname annotation matches NO
        hosted zone fails its ensure with NoSuchHostedZone — raised by
        get_hosted_zone's live-walk fallback, the source of truth, so
        the snapshot is NOT at fault and must survive: a persistently
        misconfigured object retrying on backoff must not force a full
        ListHostedZones reload for every other ensure (r4 advisor)."""
        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache
        from agac_tpu.cloudprovider.aws.errors import AWSAPIError

        backend.add_hosted_zone("example.com")
        cache = HostedZoneCache(ttl=600.0)
        driver = make_driver(backend, None)
        driver._zone_cache = cache
        svc = make_lb_service()
        ensure(driver, svc)  # the accelerator the ensure aliases
        driver.get_hosted_zone("www.example.com")  # warm the snapshot
        misses_before = cache.misses
        for _ in range(3):  # every backoff retry of the bad object
            with pytest.raises(AWSAPIError, match="NoSuchHostedZone"):
                driver.ensure_route53_for_service(
                    svc,
                    svc.status.load_balancer.ingress[0],
                    ["app.unrelated.org"],
                    "default",
                )
        # the warm snapshot survived: other ensures keep hitting it
        driver.get_hosted_zone("www.example.com")
        assert cache.misses == misses_before

    def test_ensure_invalidates_when_resolved_zone_vanishes(self, backend):
        """The counterpart: a zone that RESOLVED (from the snapshot)
        and then vanished out-of-band mid-ensure must still drop the
        snapshot so the retry re-reads."""
        from agac_tpu.cloudprovider.aws.cache import HostedZoneCache
        from agac_tpu.cloudprovider.aws.errors import AWSAPIError

        zone = backend.add_hosted_zone("example.com")
        cache = HostedZoneCache(ttl=600.0)
        driver = make_driver(backend, None)
        driver._zone_cache = cache
        svc = make_lb_service()
        ensure(driver, svc)
        driver.get_hosted_zone("www.example.com")  # warm the snapshot
        # out-of-band: the zone disappears behind the controller
        with backend._lock:
            del backend._zones[zone.id]
            del backend._records[zone.id]
        misses_before = cache.misses
        with pytest.raises(AWSAPIError, match="NoSuchHostedZone"):
            driver.ensure_route53_for_service(
                svc,
                svc.status.load_balancer.ingress[0],
                ["app.example.com"],
                "default",
            )
        # the failure dropped the snapshot: the next resolution
        # reloads (and correctly fails to find the deleted zone)
        with pytest.raises(AWSAPIError, match="NoSuchHostedZone"):
            driver.get_hosted_zone("www.example.com")
        assert cache.misses == misses_before + 1
