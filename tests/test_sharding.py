"""Unit tier for the horizontal sharding plane (ISSUE 8,
``agac_tpu/sharding/``).

Four surfaces, each with the property the tentpole's safety argument
leans on:

- **ring** — deterministic partitioning (every replica derives the
  identical map), rough balance, and the ~1/N movement bound on
  resize that makes shard-count changes an incremental migration
  instead of a full reshuffle;
- **membership** — lease acquire/renew/steal on a fake clock, with
  the exclusivity invariant held at every step: a FRESH lease is
  never stolen, a lost CAS drops the shard immediately, capacity is
  respected, clean release hands over without waiting out the lease;
- **quota division** — the AIMD ceilings rebalance with ownership and
  the fleet AGGREGATE never exceeds the global budget across
  membership churn (including mid-failover, when a shard's budget is
  briefly owned by nobody);
- **shard-filtered GC** — a sweeper only partitions candidates from
  its own keyspace: foreign orphans are neither deleted nor even
  grace-counted, and a replica owning nothing never sweeps at all.

Plus the per-shard report merge (the single-owner-assumption fix):
two shards' partial drift/GC reports merge additively instead of
last-writer-wins.
"""

from __future__ import annotations

import threading

import pytest

from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cloudprovider.aws.health import AIMDLimiter, HealthConfig, HealthTracker
from agac_tpu.cluster import FakeCluster, SharedInformerFactory
from agac_tpu.controllers import GarbageCollector, GarbageCollectorConfig
from agac_tpu.leaderelection import LeaderElectionConfig
from agac_tpu.manager import Manager
from agac_tpu.sharding import (
    OWNS_ALL,
    HashRing,
    ShardFilter,
    ShardMembership,
    ShardingConfig,
    request_resize,
    transition_plan,
)
from agac_tpu.sharding.membership import (
    ANN_KEYS_OWNED,
    RESIZE_STABLE,
)
from agac_tpu.sharding.reports import merge_shard_reports

from .fixtures import NLB_REGION, make_lb_service


# ---------------------------------------------------------------------------
# ring
# ---------------------------------------------------------------------------


class TestHashRing:
    def test_deterministic_across_instances(self):
        keys = [f"ns-{i}/svc-{i}" for i in range(500)]
        a, b = HashRing(4), HashRing(4)
        assert [a.shard_for_key(k) for k in keys] == [
            b.shard_for_key(k) for k in keys
        ]

    def test_key_form_matches_namespace_name_form(self):
        ring = HashRing(8)
        assert ring.shard_for("default", "web") == ring.shard_for_key("default/web")

    def test_rough_balance_over_uniform_keys(self):
        ring = HashRing(4)
        keys = [f"default/svc-{i:05d}" for i in range(5000)]
        buckets = ring.partition(keys)
        fair = len(keys) / ring.shard_count
        for shard, owned in buckets.items():
            assert 0.5 * fair <= len(owned) <= 1.6 * fair, (
                f"shard {shard} owns {len(owned)} of {len(keys)} "
                f"(fair share {fair:.0f})"
            )

    def test_resize_moves_about_one_nth(self):
        keys = [f"default/svc-{i:05d}" for i in range(5000)]
        before, after = HashRing(4), HashRing(5)
        moved = sum(
            1 for k in keys if before.shard_for_key(k) != after.shard_for_key(k)
        )
        # ideal movement is 1/5 of the keyspace; a modulo partitioner
        # would move ~4/5.  Pin "consistent", with slack for vnode
        # placement variance.
        assert 0.05 * len(keys) <= moved <= 0.35 * len(keys), moved

    def test_single_shard_owns_everything(self):
        ring = HashRing(1)
        assert {ring.shard_for_key(f"ns/{i}") for i in range(100)} == {0}

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            HashRing(0)
        with pytest.raises(ValueError):
            HashRing(2, vnodes=0)

    def test_version_identifies_the_map(self):
        assert HashRing(4).version == HashRing(4).version
        assert HashRing(4).version != HashRing(5).version


# ---------------------------------------------------------------------------
# ring transitions (ISSUE 10): the exact donor/gainer movement plan
# ---------------------------------------------------------------------------


class TestRingTransition:
    def test_identical_rings_move_nothing(self):
        plan = transition_plan(HashRing(4), HashRing(4))
        assert plan.moved_fraction == 0.0
        assert plan.gainers == frozenset()
        assert plan.donors == frozenset()

    def test_growth_gainers_are_exactly_the_new_shards(self):
        plan = transition_plan(HashRing(2), HashRing(4))
        # surviving shards keep their vnodes, so only the NEW shards
        # capture arcs on growth
        assert plan.gainers == {2, 3}
        assert plan.donors <= {0, 1}
        assert 0 < plan.moved_fraction < 0.75

    def test_shrink_donors_are_exactly_the_removed_shards(self):
        plan = transition_plan(HashRing(4), HashRing(2))
        assert plan.donors == {2, 3}
        assert plan.gainers <= {0, 1}

    def test_plan_agrees_with_per_key_movement(self):
        old, new = HashRing(3), HashRing(5)
        plan = transition_plan(old, new)
        keys = [f"default/svc-{i:05d}" for i in range(4000)]
        for key in keys:
            s_old, s_new = old.shard_for_key(key), new.shard_for_key(key)
            assert plan.key_moves(key) == (s_old != s_new)
            if s_old != s_new:
                assert s_new in plan.gainers_of[s_old]
                assert s_old in plan.donors_of[s_new]
        measured = sum(plan.key_moves(k) for k in keys) / len(keys)
        # the sampled movement tracks the exact arc measure
        assert abs(measured - plan.moved_fraction) < 0.05

    def test_vnode_mismatch_rejected(self):
        with pytest.raises(ValueError):
            transition_plan(HashRing(2, vnodes=32), HashRing(4, vnodes=64))


# ---------------------------------------------------------------------------
# filter
# ---------------------------------------------------------------------------


class TestShardFilter:
    def test_owns_all_is_single_shard_semantics(self):
        assert OWNS_ALL.owns("any-ns", "any-name")
        assert OWNS_ALL.owns_key("whatever/key")
        assert OWNS_ALL.token() == "all"
        assert OWNS_ALL.owned_shards() == frozenset({0})

    def test_filter_partitions_exactly_by_ring(self):
        ring = HashRing(3)
        owned = frozenset({1})
        shard_filter = ShardFilter(ring, lambda: owned)
        for i in range(200):
            key = f"default/svc-{i}"
            assert shard_filter.owns_key(key) == (ring.shard_for_key(key) == 1)

    def test_token_tracks_live_ownership(self):
        owned = {"value": frozenset()}
        shard_filter = ShardFilter(HashRing(4), lambda: owned["value"])
        assert shard_filter.token() == "none"
        owned["value"] = frozenset({2, 0})
        assert shard_filter.token() == "0,2"


# ---------------------------------------------------------------------------
# membership (fake clock, FakeCluster leases)
# ---------------------------------------------------------------------------

FAST_LEASE = LeaderElectionConfig(
    lease_duration=6.0, renew_deadline=2.0, retry_period=1.0
)


class MembershipWorld:
    """N replicas' memberships over one shared FakeCluster, ticked
    explicitly on a fake clock — the cooperative form the sim harness
    schedules, without a scheduler."""

    def __init__(self, shard_count=2, capacity=2, replicas=("a", "b"), **config_overrides):
        self.cluster = FakeCluster()
        self.now = 0.0
        config = ShardingConfig(
            shard_count=shard_count,
            shards_per_replica=capacity,
            lease=FAST_LEASE,
            **config_overrides,
        )
        self.members = {
            identity: ShardMembership(
                config, identity=identity, clock=lambda: self.now
            )
            for identity in replicas
        }

    def tick(self, *identities):
        for identity in identities or self.members:
            self.members[identity].tick(self.cluster)

    def full_tick(self, *identities):
        """tick + the manager's resize role: run the (out-of-band)
        resync and ack adoptions — what ``Manager.shard_tick`` does."""
        for identity in identities or self.members:
            member = self.members[identity]
            member.tick(self.cluster)
            if member.resync_pending():
                member.ack_adoptions(self.cluster)

    def advance(self, seconds: float):
        self.now += seconds

    def owned(self, identity: str) -> set:
        return set(self.members[identity].owned_shards())

    def assert_exclusive(self):
        seen: dict[int, str] = {}
        for identity, member in self.members.items():
            for shard in member.owned_shards():
                assert shard not in seen, (
                    f"shard {shard} owned by both {seen[shard]} and {identity}"
                )
                seen[shard] = identity

    def assert_key_exclusive(self, keys):
        """Key-level effective-ownership exclusivity — must hold at
        EVERY step of a transition, not just the endpoints."""
        for key in keys:
            owners = [
                identity
                for identity, member in self.members.items()
                if member.filter.owns_key(key)
            ]
            assert len(owners) <= 1, f"key {key} owned by {owners}"


class TestShardMembership:
    def test_one_claim_per_tick_balances_two_replicas(self):
        world = MembershipWorld()
        world.tick("a", "b")
        assert world.owned("a") == {0}
        assert world.owned("b") == {1}
        world.assert_exclusive()

    def test_fresh_lease_never_stolen(self):
        world = MembershipWorld()
        world.tick("a", "b")
        # both keep renewing every retry_period: ownership is stable
        for _ in range(20):
            world.advance(FAST_LEASE.retry_period)
            world.tick("a", "b")
            assert world.owned("a") == {0} and world.owned("b") == {1}
            world.assert_exclusive()

    def test_expired_lease_stolen_and_counted(self):
        world = MembershipWorld()
        world.tick("a", "b")
        steals_before = world.members["a"]._m_steals.value()
        # b crashes (stops ticking); a steals only after the lease
        # expires on a's local observation clock
        for _ in range(int(FAST_LEASE.lease_duration) - 1):
            world.advance(1.0)
            world.tick("a")
            world.assert_exclusive()
        assert world.owned("a") == {0}, "lease must not be stolen while fresh"
        world.advance(2.0)
        world.tick("a")
        assert world.owned("a") == {0, 1}
        assert world.members["a"]._m_steals.value() == steals_before + 1

    def test_lost_cas_drops_shard_immediately(self):
        world = MembershipWorld()
        world.tick("a", "b")
        # b pauses; a keeps ticking — the steal lands one full
        # lease_duration after a FIRST OBSERVED b's record (client-go
        # observed-time semantics, so a single late tick can't steal)
        for _ in range(int(FAST_LEASE.lease_duration) + 2):
            world.advance(1.0)
            world.tick("a")
        assert world.owned("a") == {0, 1}
        # b wakes up and ticks: its renew CAS must fail against a's
        # fresh hold and b must drop the shard in the same tick
        world.tick("b")
        assert world.owned("b") == set()
        world.assert_exclusive()

    def test_clean_release_hands_over_without_expiry_wait(self):
        world = MembershipWorld()
        world.tick("a", "b")
        world.members["b"].release_all(world.cluster)
        assert world.owned("b") == set()
        # a claims the released lease on its next tick — no
        # lease_duration wait
        world.advance(FAST_LEASE.retry_period)
        world.tick("a")
        assert world.owned("a") == {0, 1}

    def test_capacity_cap_respected(self):
        world = MembershipWorld(shard_count=4, capacity=1, replicas=("a",))
        for _ in range(10):
            world.tick("a")
            world.advance(FAST_LEASE.retry_period)
        assert len(world.owned("a")) == 1

    def test_quota_fraction_follows_ownership(self):
        world = MembershipWorld()
        assert world.members["a"].quota_fraction() == 0.0
        world.tick("a", "b")
        assert world.members["a"].quota_fraction() == 0.5
        for _ in range(int(FAST_LEASE.lease_duration) + 2):
            world.advance(1.0)
            world.tick("a")
        assert world.members["a"].quota_fraction() == 1.0

    def test_shard_map_publishes_observed_holders(self):
        world = MembershipWorld()
        world.tick("a", "b")
        world.tick("a")  # a's capacity probe observes b's hold
        shard_map = world.members["a"].shard_map()
        assert shard_map["owned"] == [0]
        assert shard_map["holders"]["0"] == "a"
        assert shard_map["holders"]["1"] == "b"
        assert shard_map["live_shards"] == 2
        assert shard_map["ring"] == "2x64"

    def test_on_change_fires_per_ownership_change(self):
        changes = []
        config = ShardingConfig(shard_count=2, lease=FAST_LEASE)
        cluster = FakeCluster()
        member = ShardMembership(
            config, identity="solo", clock=lambda: 0.0,
            on_change=lambda m: changes.append(sorted(m.owned_shards())),
        )
        member.tick(cluster)
        member.tick(cluster)
        member.tick(cluster)  # no further change once both are held
        assert changes == [[0], [0, 1]]


# ---------------------------------------------------------------------------
# elastic resharding (ISSUE 10): the drain/handoff state machine on a
# fake clock
# ---------------------------------------------------------------------------


SAMPLE_KEYS = [f"default/svc-{i:04d}" for i in range(120)]


def settle_resize(world, target, max_ticks=40):
    """Tick every member (with the manager's ack role) until all run
    the stable target ring, asserting key-level exclusivity at EVERY
    step; returns ticks taken."""
    for tick in range(max_ticks):
        world.full_tick()
        world.assert_exclusive()
        world.assert_key_exclusive(SAMPLE_KEYS)
        if all(
            member.resize_status()["state"] == RESIZE_STABLE
            and member.shard_count == target
            and not member.resize_status()["handoff_pending"]
            for member in world.members.values()
        ):
            return tick
        world.advance(FAST_LEASE.retry_period)
    raise AssertionError(
        f"resize to {target} never settled: "
        f"{[m.resize_status() for m in world.members.values()]}"
    )


class TestElasticResize:
    def test_grow_2_to_4_two_phase_drain_then_adopt(self):
        world = MembershipWorld(capacity=4)
        world.tick("a", "b")
        assert world.owned("a") == {0} and world.owned("b") == {1}
        epoch = request_resize(world.cluster, 4)
        assert epoch == 1
        settle_resize(world, 4)
        # every shard of the new ring held, split across both replicas
        held = world.owned("a") | world.owned("b")
        assert held == {0, 1, 2, 3}
        world.assert_exclusive()
        # every key owned by exactly one replica post-resize
        owners = [
            sum(m.filter.owns_key(k) for m in world.members.values())
            for k in SAMPLE_KEYS
        ]
        assert all(count == 1 for count in owners)
        for member in world.members.values():
            assert member.ring.version == "4x64"
            assert member.resizes_completed == 1

    def test_no_key_double_owned_and_unowned_bounded_during_transition(self):
        world = MembershipWorld(capacity=4)
        world.tick("a", "b")
        request_resize(world.cluster, 4)
        unowned_streak = {key: 0 for key in SAMPLE_KEYS}
        worst = 0
        for _ in range(40):
            world.full_tick()
            world.assert_key_exclusive(SAMPLE_KEYS)
            for key in SAMPLE_KEYS:
                owned = any(
                    m.filter.owns_key(key) for m in world.members.values()
                )
                unowned_streak[key] = 0 if owned else unowned_streak[key] + 1
                worst = max(worst, unowned_streak[key])
            if all(
                m.resize_status()["state"] == RESIZE_STABLE
                and m.shard_count == 4
                for m in world.members.values()
            ):
                break
            world.advance(FAST_LEASE.retry_period)
        # with both sides live the drain→adopt gap is tick-bounded:
        # one handoff window, never a lease expiry
        assert 0 < worst <= 4, worst
        assert all(streak == 0 for streak in unowned_streak.values())

    def test_shrink_4_to_2_releases_obsolete_leases(self):
        world = MembershipWorld(shard_count=4, capacity=4)
        for _ in range(4):
            world.tick("a", "b")
            world.advance(FAST_LEASE.retry_period)
        assert world.owned("a") | world.owned("b") == {0, 1, 2, 3}
        request_resize(world.cluster, 2)
        settle_resize(world, 2)
        held = world.owned("a") | world.owned("b")
        assert held == {0, 1}
        # the obsolete leases were RELEASED, not abandoned: unheld on
        # the cluster record
        for shard in (2, 3):
            lease = world.cluster.get("Lease", "kube-system", f"agac-shard-{shard}")
            assert not lease.spec.holder_identity

    def test_resize_request_refused_while_in_flight(self):
        world = MembershipWorld(capacity=4)
        world.tick("a", "b")
        request_resize(world.cluster, 4)
        world.full_tick()  # transition armed, not complete
        with pytest.raises(RuntimeError, match="in flight"):
            request_resize(world.cluster, 8)
        settle_resize(world, 4)
        # once complete, the next resize is accepted
        assert request_resize(world.cluster, 2) == 2

    def test_resize_is_idempotent_at_current_count(self):
        world = MembershipWorld(capacity=4)
        world.tick("a", "b")
        epoch = request_resize(world.cluster, 4)
        settle_resize(world, 4)
        assert request_resize(world.cluster, 4) == epoch

    def test_dead_donor_mid_resize_survivor_completes(self):
        """kill -9 semantics during an in-flight resize: b stops
        ticking after the transition starts (its leases stay held);
        a steals them after expiry, self-drains/adopts, and COMPLETES
        the transition alone."""
        world = MembershipWorld(capacity=4)
        world.tick("a", "b")
        request_resize(world.cluster, 4)
        world.full_tick("a", "b")  # both observe the transition
        # b dies here; a keeps ticking
        for _ in range(int(FAST_LEASE.lease_duration) + 30):
            world.full_tick("a")
            # only a's view may be asserted — b is "dead" but its
            # stale membership object still holds python state
            member = world.members["a"]
            world.advance(1.0)
            if (
                member.resize_status()["state"] == RESIZE_STABLE
                and member.shard_count == 4
            ):
                break
        member = world.members["a"]
        assert member.shard_count == 4
        assert member.resize_status()["state"] == RESIZE_STABLE
        assert world.owned("a") == {0, 1, 2, 3}
        assert member.resizes_completed == 1

    def test_resize_status_shape_through_transition(self):
        world = MembershipWorld(capacity=4)
        world.tick("a", "b")
        status = world.members["a"].resize_status()
        assert status["state"] == RESIZE_STABLE
        assert status["handoff_pending"] == 0
        assert status["ring"] == "2x64"
        request_resize(world.cluster, 4)
        world.full_tick()
        status = world.members["a"].resize_status()
        assert status["state"] in ("draining", "adopting")
        assert status["from"] == 2 and status["to"] == 4
        assert status["target_ring"] == "4x64"
        assert status["handoff_pending"] >= 1
        assert "pending_gainers" in status and "drained" in status
        settle_resize(world, 4)
        final = world.members["a"].resize_status()
        assert final["state"] == RESIZE_STABLE
        assert final["ring"] == "4x64"
        assert final["handoff_pending"] == 0


# ---------------------------------------------------------------------------
# load-aware preferred-owner placement (ISSUE 10)
# ---------------------------------------------------------------------------


class TestLoadAwarePlacement:
    def wire_counts(self, world, counts: dict[int, int]):
        for member in world.members.values():
            member.fleet_key_counts = lambda c=counts: dict(c)

    def test_claims_prefer_the_heaviest_unclaimed_shard(self):
        world = MembershipWorld(shard_count=4, capacity=4, replicas=("a",))
        self.wire_counts(world, {0: 1, 1: 9, 2: 5, 3: 7})
        order = []
        for _ in range(4):
            before = world.owned("a")
            world.tick("a")
            gained = world.owned("a") - before
            order.extend(sorted(gained))
            world.advance(FAST_LEASE.retry_period)
        assert order == [1, 3, 2, 0], order

    def test_overloaded_replica_abstains_until_availability_grace(self):
        world = MembershipWorld(
            shard_count=3, capacity=3, replicas=("a", "b"),
            rebalance_hysteresis_keys=2, unheld_grace_ticks=3,
        )
        self.wire_counts(world, {0: 20, 1: 1, 2: 1})
        world.tick("a")  # a claims 0 (heaviest), publishing load 20
        world.tick("b")  # b claims 1
        assert world.owned("a") == {0} and world.owned("b") == {1}
        # a is far heavier than b: a must leave shard 2 for b even
        # while below capacity...
        world.advance(FAST_LEASE.retry_period)
        world.tick("a")
        assert world.owned("a") == {0}, "overloaded replica must abstain"
        # ...but if nobody claims it past the grace, availability wins
        for _ in range(4):
            world.advance(FAST_LEASE.retry_period)
            world.tick("a")
        assert world.owned("a") == {0, 2}

    def test_shed_converges_and_does_not_oscillate(self):
        world = MembershipWorld(
            shard_count=4, capacity=4, replicas=("a", "b"),
            rebalance_hysteresis_keys=3, rebalance_cooldown_ticks=3,
        )
        counts = {0: 10, 1: 10, 2: 1, 3: 1}
        self.wire_counts(world, counts)
        # a vacuums everything before b joins
        for _ in range(4):
            world.tick("a")
            world.advance(FAST_LEASE.retry_period)
        assert world.owned("a") == {0, 1, 2, 3}
        # b joins: a (load 22) sheds toward b (load 0); track the
        # handover count to prove convergence without oscillation
        transfers = 0
        previous = {"a": world.owned("a"), "b": world.owned("b")}
        for _ in range(40):
            world.tick("a", "b")
            world.assert_exclusive()
            current = {"a": world.owned("a"), "b": world.owned("b")}
            if current != previous:
                transfers += 1
                previous = current
            world.advance(FAST_LEASE.retry_period)
        load = {
            identity: sum(counts[s] for s in world.owned(identity))
            for identity in ("a", "b")
        }
        # balanced within the hysteresis, and the system SETTLED (a
        # bounded number of ownership changes, not a ping-pong)
        assert abs(load["a"] - load["b"]) <= 3 + max(counts.values()), load
        assert world.owned("b"), "b must have received load"
        assert transfers <= 8, f"placement oscillated: {transfers} changes"
        # a never re-claims what it shed within the cooldown: final
        # state stays stable over further ticks
        stable = {"a": world.owned("a"), "b": world.owned("b")}
        for _ in range(6):
            world.tick("a", "b")
            world.advance(FAST_LEASE.retry_period)
        assert {"a": world.owned("a"), "b": world.owned("b")} == stable

    def test_lease_records_publish_keys_owned(self):
        world = MembershipWorld(shard_count=2, capacity=2, replicas=("a",))
        self.wire_counts(world, {0: 7, 1: 3})
        world.tick("a")
        world.advance(FAST_LEASE.retry_period)
        world.tick("a")
        lease = world.cluster.get("Lease", "kube-system", "agac-shard-0")
        # a holds both shards by now: published load = 7 or 10
        # depending on claim order; the annotation must exist and be
        # an integer
        assert int(lease.metadata.annotations[ANN_KEYS_OWNED]) >= 7


# ---------------------------------------------------------------------------
# filter memoization (ISSUE 10 satellite): the ring walk runs once per
# (ring, key)
# ---------------------------------------------------------------------------


class TestFilterMemoization:
    def test_memo_returns_identical_answers(self):
        ring = HashRing(4)
        shard_filter = ShardFilter(ring, lambda: frozenset({1, 2}))
        keys = [f"default/svc-{i}" for i in range(500)]
        first = [shard_filter.owns_key(k) for k in keys]
        second = [shard_filter.owns_key(k) for k in keys]
        assert first == second
        assert first == [ring.shard_for_key(k) in {1, 2} for k in keys]

    def test_memo_hits_skip_the_ring_walk(self):
        ring = HashRing(8)
        shard_filter = ShardFilter(ring, lambda: frozenset({0}))
        shard_filter.owns_key("default/hot-key")
        calls = {"n": 0}
        original = ring.shard_for_key

        def counting(key):
            calls["n"] += 1
            return original(key)

        ring.shard_for_key = counting
        for _ in range(100):
            shard_filter.owns_key("default/hot-key")
        assert calls["n"] == 0, "memoized lookups must not re-walk the ring"

    def test_memo_invalidates_across_ring_versions(self):
        rings = {"ring": HashRing(2)}
        shard_filter = ShardFilter(
            None, lambda: frozenset({0, 1, 2, 3}),
            ring_provider=lambda: rings["ring"],
        )
        key = "default/svc-x"
        assert shard_filter.owns_key(key)
        # swap the live ring (a completed resize): lookups must follow
        # the NEW ring even for memoized keys
        rings["ring"] = HashRing(8)
        expected = HashRing(8).shard_for_key(key)
        shard_filter_owned = ShardFilter(
            None, lambda: frozenset({expected}),
            ring_provider=lambda: rings["ring"],
        )
        assert shard_filter_owned.owns_key(key)
        shard_filter_foreign = ShardFilter(
            None, lambda: frozenset({(expected + 1) % 8}),
            ring_provider=lambda: rings["ring"],
        )
        assert not shard_filter_foreign.owns_key(key)


# ---------------------------------------------------------------------------
# quota division (the health plane's AIMD seam)
# ---------------------------------------------------------------------------


class TestQuotaDivision:
    def test_set_ceiling_clamps_live_rate_down(self):
        limiter = AIMDLimiter(qps=20.0, floor=0.5)
        assert limiter.rate() == 20.0
        limiter.set_ceiling(5.0)
        assert limiter.ceiling() == 5.0
        assert limiter.rate() == 5.0
        # growth is earned back additively, capped at the new ceiling
        for _ in range(100):
            limiter.on_success()
        assert limiter.rate() == 5.0

    def test_set_ceiling_floor_clamped(self):
        limiter = AIMDLimiter(qps=20.0, floor=0.5)
        limiter.set_ceiling(0.0)
        assert limiter.ceiling() == 0.5

    def test_tracker_rebalances_existing_and_future_services(self):
        tracker = HealthTracker(
            config=HealthConfig(aimd_qps=20.0), sleep=lambda s: None
        )
        existing = tracker.service("globalaccelerator")
        tracker.set_quota_fraction(0.25)
        assert existing.limiter.ceiling() == 5.0
        later = tracker.service("route53")
        assert later.limiter.ceiling() == 5.0
        assert tracker.quota_fraction() == 0.25
        assert existing.snapshot()["aimd_ceiling"] == 5.0

    def test_aggregate_never_exceeds_global_budget_across_churn(self):
        """Two replicas' trackers, driven by their memberships through
        every phase of a failover: the sum of LIVE shard-owner
        ceilings stays <= the global budget at every step (the
        mid-steal dip is unowned budget, never double-counted; a dead
        replica's stale owned set counts for nothing because nothing
        of it runs)."""
        global_qps = 20.0
        world = MembershipWorld()
        live = {"a", "b"}
        trackers = {
            identity: HealthTracker(
                config=HealthConfig(aimd_qps=global_qps), sleep=lambda s: None
            )
            for identity in world.members
        }
        for identity, member in world.members.items():
            tracker = trackers[identity]
            member.on_change = (
                lambda m, t=tracker: t.set_quota_fraction(m.quota_fraction())
            )
            tracker.set_quota_fraction(0.0)

        def aggregate_owner_ceiling() -> float:
            total = 0.0
            for identity, member in world.members.items():
                if identity not in live or not member.owned_shards():
                    continue  # dead replicas run nothing; ownerless idle
                service = trackers[identity].service("globalaccelerator")
                total += service.limiter.ceiling()
            return total

        assert aggregate_owner_ceiling() == 0.0
        world.tick("a", "b")  # balanced: 10 + 10
        assert aggregate_owner_ceiling() == pytest.approx(global_qps)
        # b crashes; until the steal lands, its budget is simply unowned
        live.discard("b")
        for _ in range(int(FAST_LEASE.lease_duration) + 2):
            world.advance(1.0)
            world.tick("a")
            assert aggregate_owner_ceiling() <= global_qps + 1e-9
        # post-failover: a owns everything at the full global budget
        assert world.owned("a") == {0, 1}
        assert aggregate_owner_ceiling() == pytest.approx(global_qps)

    def test_revived_replica_drops_budget_with_its_shards(self):
        """The resurrection case: a replica paused past its lease
        expiry wakes up AFTER its shards were stolen — its very next
        tick fails the renew CAS, drops the shards, and its quota
        fraction collapses to zero, so the post-revival aggregate is
        back under the global budget within one tick."""
        global_qps = 20.0
        world = MembershipWorld()
        trackers = {
            identity: HealthTracker(
                config=HealthConfig(aimd_qps=global_qps), sleep=lambda s: None
            )
            for identity in world.members
        }
        for identity, member in world.members.items():
            member.on_change = (
                lambda m, t=trackers[identity]: t.set_quota_fraction(
                    m.quota_fraction()
                )
            )
        world.tick("a", "b")
        for _ in range(int(FAST_LEASE.lease_duration) + 2):
            world.advance(1.0)
            world.tick("a")  # b paused; a steals shard 1
        assert world.owned("a") == {0, 1}
        world.tick("b")  # b revives: CAS fails, shard + budget dropped
        assert world.owned("b") == set()
        assert trackers["b"].quota_fraction() == 0.0
        total = sum(
            trackers[i].service("ga").limiter.ceiling()
            for i in world.members
            if world.owned(i)
        )
        assert total == pytest.approx(global_qps)


# ---------------------------------------------------------------------------
# shard-filtered GC candidate partition
# ---------------------------------------------------------------------------


def nlb_hostname(i: int) -> str:
    return f"lb{i}-0123456789abcdef.elb.{NLB_REGION}.amazonaws.com"


class GCWorld:
    """The test_gc_sweeper World, narrowed to the partition surface."""

    def __init__(self):
        self.cluster = FakeCluster()
        self.aws = FakeAWSBackend(quota_accelerators=100)
        self.zone = self.aws.add_hosted_zone("example.com")
        self.stop = threading.Event()
        self.factory = SharedInformerFactory(self.cluster, resync_period=30.0)
        self.factory.informer("Service")
        self.factory.informer("Ingress")
        self.factory.start(self.stop)
        assert self.factory.wait_for_cache_sync(self.stop)
        self.driver = AWSDriver(
            self.aws, self.aws, self.aws, poll_interval=0.01, poll_timeout=2.0
        )

    def gc(self, shard_filter=None, **overrides) -> GarbageCollector:
        overrides.setdefault("grace_sweeps", 1)
        config = GarbageCollectorConfig(interval=0.01, **overrides)
        return GarbageCollector(
            self.factory, config, lambda region: self.driver,
            shard_filter=shard_filter,
        )

    def make_orphan(self, name: str, i: int):
        self.aws.add_load_balancer(f"lb{i}", NLB_REGION, nlb_hostname(i))
        svc = make_lb_service(name=name, hostname=nlb_hostname(i))
        arn, _, _ = self.driver.ensure_global_accelerator_for_service(
            svc, svc.status.load_balancer.ingress[0], "default", f"lb{i}", NLB_REGION
        )
        return arn


@pytest.fixture
def gc_world():
    world = GCWorld()
    yield world
    world.stop.set()


class TestShardFilteredGC:
    def test_sweeper_only_partitions_owned_candidates(self, gc_world):
        ring = HashRing(2)
        orphans = {}
        for i in range(8):
            name = f"ghost{i}"
            orphans[name] = gc_world.make_orphan(name, i)
        owned_names = {
            name for name in orphans if ring.shard_for("default", name) == 0
        }
        assert 0 < len(owned_names) < len(orphans), "need a real split"
        shard_filter = ShardFilter(ring, lambda: frozenset({0}))
        report = gc_world.gc(shard_filter=shard_filter).sweep_once()
        assert report["shards"] == "0"
        assert report["candidates"]["accelerators"] == len(owned_names)
        assert report["deleted"]["accelerators"] == len(owned_names)
        # foreign-shard orphans survive untouched — the other shard's
        # sweeper owns them
        survivors = set(gc_world.aws.all_accelerator_arns())
        assert survivors == {
            arn for name, arn in orphans.items() if name not in owned_names
        }

    def test_foreign_candidates_accrue_no_grace_state(self, gc_world):
        ring = HashRing(2)
        gc_world.make_orphan("ghost0", 0)
        foreign_shard = 1 - ring.shard_for("default", "ghost0")
        shard_filter = ShardFilter(ring, lambda: frozenset({foreign_shard}))
        gc = gc_world.gc(shard_filter=shard_filter, grace_sweeps=2)
        for _ in range(3):
            report = gc.sweep_once()
            assert report["candidates"] == {"accelerators": 0, "records": 0}
        assert gc._pending_accelerators == {}

    def test_replica_owning_nothing_never_sweeps(self, gc_world):
        gc_world.make_orphan("ghost0", 0)
        calls_before = len(gc_world.aws.calls)
        shard_filter = ShardFilter(HashRing(2), lambda: frozenset())
        report = gc_world.gc(shard_filter=shard_filter).sweep_once()
        assert report["skipped_no_shards"] is True
        assert report["candidates"] == {"accelerators": 0, "records": 0}
        assert len(gc_world.aws.calls) == calls_before, (
            "a shardless replica must not spend quota enumerating"
        )


# ---------------------------------------------------------------------------
# per-shard report merge (the single-owner-assumption fix)
# ---------------------------------------------------------------------------


class TestPerShardReports:
    def test_merge_adds_counts_unions_lists_ors_bools(self):
        merged = merge_shard_reports(
            {
                "0": {
                    "shards": "0",
                    "enqueued": {"ga": 2},
                    "skipped": {},
                    "partial": False,
                    "listing_failed": ["records"],
                },
                "1": {
                    "shards": "1",
                    "enqueued": {"ga": 3, "r53": 1},
                    "skipped": {"r53": ["route53"]},
                    "partial": True,
                    "listing_failed": ["records", "accelerators"],
                },
            }
        )
        assert merged == {
            "enqueued": {"ga": 5, "r53": 1},
            "skipped": {"r53": ["route53"]},
            "partial": True,
            "listing_failed": ["records", "accelerators"],
        }

    def test_drift_reports_keyed_per_shard_not_overwritten(self):
        class FakeController:
            DRIFT_SERVICES = ()

            def __init__(self):
                self.enqueued = []

            def drift_resync_sources(self):
                class Lister:
                    @staticmethod
                    def list():
                        return ["x", "y"]

                return [(Lister, lambda o: True, self.enqueued.append)]

        manager = Manager()
        manager.controllers = {"c": FakeController()}
        manager.shard_filter = ShardFilter(HashRing(2), lambda: frozenset({0}))
        manager.drift_tick()
        manager.shard_filter = ShardFilter(HashRing(2), lambda: frozenset({1}))
        manager.drift_tick()
        assert set(manager.last_drift_reports) == {"0", "1"}
        # the merged legacy view ADDS the two partials instead of
        # showing whichever shard ticked last
        assert manager.last_drift_report["enqueued"] == {"c": 4}
