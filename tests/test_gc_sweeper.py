"""Unit tier for the orphan GC sweeper (ISSUE 4 tentpole,
``agac_tpu/controllers/garbagecollector.py``).

The sweeper deletes resources nobody asked it to touch, so this tier
is mostly about the FAIL-CLOSED rails: the grace-period state machine
(consecutive observation before deletion), the per-sweep deletion
budget, refusing to conclude anything from an unsynced informer or a
failed listing, dry-run mode, circuit-open skips, adoption of
re-created owners, and never touching resources whose ownership
cannot be parsed.  The /healthz surfacing of the sweep counters is
pinned here too.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from agac_tpu import apis
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cloudprovider.aws.driver import (
    CLUSTER_TAG_KEY,
    MANAGED_TAG_KEY,
    OWNER_TAG_KEY,
)
from agac_tpu.cloudprovider.aws.fake_backend import FaultPlan
from agac_tpu.cloudprovider.aws.health import (
    OUTCOME_SERVER_ERROR,
    HealthConfig,
    HealthTracker,
)
from agac_tpu.cloudprovider.aws.types import Tag
from agac_tpu.cluster import FakeCluster, SharedInformerFactory
from agac_tpu.controllers import GarbageCollector, GarbageCollectorConfig
from agac_tpu.manager import make_health_server

from .fixtures import NLB_REGION, make_lb_service


def nlb_hostname(i: int) -> str:
    return f"lb{i}-0123456789abcdef.elb.{NLB_REGION}.amazonaws.com"


def wait_until(pred, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class World:
    """Cluster + fake AWS + synced informers + a driver — the sweeping
    surface without any reactive controllers running."""

    def __init__(self, synced: bool = True):
        self.cluster = FakeCluster()
        self.aws = FakeAWSBackend(quota_accelerators=100)
        self.zone = self.aws.add_hosted_zone("example.com")
        self.stop = threading.Event()
        self.factory = SharedInformerFactory(self.cluster, resync_period=30.0)
        self.factory.informer("Service")
        self.factory.informer("Ingress")
        if synced:
            self.factory.start(self.stop)
            assert self.factory.wait_for_cache_sync(self.stop)
        self.driver = AWSDriver(
            self.aws, self.aws, self.aws, poll_interval=0.01, poll_timeout=2.0
        )

    def gc(self, health=None, **overrides) -> GarbageCollector:
        config = GarbageCollectorConfig(interval=0.01, **overrides)
        return GarbageCollector(
            self.factory, config, lambda region: self.driver, health=health
        )

    def make_orphan(self, i: int = 0, hostnames: tuple = ()):
        """A full accelerator chain (and optional TXT/A record pairs)
        whose Kubernetes owner does NOT exist in the cluster — the
        exact state a Service deleted during a controller outage
        leaves behind."""
        self.aws.add_load_balancer(f"lb{i}", NLB_REGION, nlb_hostname(i))
        svc = make_lb_service(name=f"ghost{i}", hostname=nlb_hostname(i))
        arn, _, _ = self.driver.ensure_global_accelerator_for_service(
            svc, svc.status.load_balancer.ingress[0], "default", f"lb{i}", NLB_REGION
        )
        for hostname in hostnames:
            created, _ = self.driver.ensure_route53_for_service(
                svc, svc.status.load_balancer.ingress[0], [hostname], "default"
            )
            assert created
        return arn, svc

    def record_names(self) -> set:
        return {(r.name, r.type) for r in self.aws.records_in_zone(self.zone.id)}


@pytest.fixture
def world():
    w = World()
    yield w
    w.stop.set()


class TestGraceStateMachine:
    def test_orphan_needs_consecutive_sweeps_before_deletion(self, world):
        arn, _ = world.make_orphan(0, hostnames=("app0.example.com",))
        gc = world.gc(grace_sweeps=2)

        report = gc.sweep_once()
        assert report["candidates"] == {"accelerators": 1, "records": 1}
        assert report["grace_held"] == 2
        assert report["deleted"] == {"accelerators": 0, "records": 0}
        assert world.aws.all_accelerator_arns() == [arn]  # grace held

        report = gc.sweep_once()
        assert report["deleted"] == {"accelerators": 1, "records": 1}
        assert world.aws.all_accelerator_arns() == []
        assert world.record_names() == set()  # TXT and A both gone

    def test_live_owner_is_never_a_candidate(self, world):
        world.aws.add_load_balancer("lb0", NLB_REGION, nlb_hostname(0))
        svc = make_lb_service(name="alive", hostname=nlb_hostname(0))
        world.cluster.create("Service", svc)
        assert wait_until(
            lambda: gc_sees_service(world, "alive")
        ), "informer never saw the Service"
        world.driver.ensure_global_accelerator_for_service(
            svc, svc.status.load_balancer.ingress[0], "default", "lb0", NLB_REGION
        )
        gc = world.gc(grace_sweeps=1)
        for _ in range(3):
            report = gc.sweep_once()
            assert report["candidates"] == {"accelerators": 0, "records": 0}
            assert report["deleted"] == {"accelerators": 0, "records": 0}
        assert len(world.aws.all_accelerator_arns()) == 1

    def test_recreated_owner_is_adopted_not_deleted(self, world):
        arn, svc = world.make_orphan(0)
        gc = world.gc(grace_sweeps=2)
        report = gc.sweep_once()
        assert report["grace_held"] == 1

        # the owner comes back (Service re-created with the same name)
        # between observation and deletion: adopt, never delete
        world.cluster.create("Service", svc)
        assert wait_until(lambda: gc_sees_service(world, "ghost0"))
        report = gc.sweep_once()
        assert report["adopted"] == 1
        assert report["deleted"] == {"accelerators": 0, "records": 0}
        assert world.aws.all_accelerator_arns() == [arn]
        assert gc.status()["pending"] == {"accelerators": 0, "records": 0}

    def test_disappearing_candidate_resets_grace(self, world):
        arn, _ = world.make_orphan(0)
        gc = world.gc(grace_sweeps=2)
        gc.sweep_once()
        assert gc.status()["pending"]["accelerators"] == 1
        # the orphan vanishes out-of-band (another actor deleted it):
        # the pending entry is dropped, not carried toward deletion
        world.driver.cleanup_global_accelerator(arn)
        gc.sweep_once()
        assert gc.status()["pending"]["accelerators"] == 0


class TestBudgetAndDryRun:
    def test_deletion_budget_clamps_each_sweep(self, world):
        for i in range(5):
            world.make_orphan(i)
        gc = world.gc(grace_sweeps=1, max_deletes=2)

        report = gc.sweep_once()
        assert report["deleted"]["accelerators"] == 2
        assert report["budget_deferred"] == 3
        assert len(world.aws.all_accelerator_arns()) == 3

        report = gc.sweep_once()
        assert report["deleted"]["accelerators"] == 2
        report = gc.sweep_once()
        assert report["deleted"]["accelerators"] == 1
        assert world.aws.all_accelerator_arns() == []

    def test_budget_is_shared_across_accelerators_and_records(self, world):
        world.make_orphan(0, hostnames=("app0.example.com",))
        gc = world.gc(grace_sweeps=1, max_deletes=1)
        report = gc.sweep_once()
        # one deletion total: the record owner waits for the next sweep
        assert report["deleted"]["accelerators"] + report["deleted"]["records"] == 1
        assert report["budget_deferred"] == 1
        report = gc.sweep_once()
        assert report["deleted"]["accelerators"] + report["deleted"]["records"] == 1
        assert world.aws.all_accelerator_arns() == []
        assert world.record_names() == set()

    def test_dry_run_observes_but_never_deletes(self, world):
        arn, _ = world.make_orphan(0, hostnames=("app0.example.com",))
        gc = world.gc(grace_sweeps=1, dry_run=True)
        for _ in range(3):
            report = gc.sweep_once()
            assert report["would_delete"] == 2  # accelerator + record owner
            assert report["deleted"] == {"accelerators": 0, "records": 0}
        assert world.aws.all_accelerator_arns() == [arn]
        assert world.record_names() != set()

        # flipping dry-run off deletes what dry-run kept observing
        live = world.gc(grace_sweeps=1, dry_run=False)
        live.sweep_once()
        assert world.aws.all_accelerator_arns() == []
        assert world.record_names() == set()


class TestFailClosedRails:
    def test_unsynced_informers_skip_the_sweep(self):
        w = World(synced=False)
        try:
            w.make_orphan(0, hostnames=("app0.example.com",))
            gc = w.gc(grace_sweeps=1)
            report = gc.sweep_once()
            assert report["skipped_unsynced"] is True
            assert report["candidates"] == {"accelerators": 0, "records": 0}
            assert len(w.aws.all_accelerator_arns()) == 1
            assert gc.status()["pending"] == {"accelerators": 0, "records": 0}
        finally:
            w.stop.set()

    def test_failed_listing_freezes_grace_state(self, world):
        world.make_orphan(0)
        gc = world.gc(grace_sweeps=2)
        gc.sweep_once()  # observation 1

        plan = world.aws.install_fault_plan(FaultPlan(exempt_creator=False))
        plan.outage("list_accelerators")
        report = gc.sweep_once()
        assert report["listing_failed"] == ["accelerators"]
        assert report["deleted"] == {"accelerators": 0, "records": 0}
        # the failed sweep neither advanced nor reset the counter
        assert gc.status()["pending"]["accelerators"] == 1

        plan.restore()
        report = gc.sweep_once()  # observation 2 — grace met
        assert report["deleted"]["accelerators"] == 1
        assert world.aws.all_accelerator_arns() == []

    def test_open_circuit_skips_the_phase(self, world):
        world.make_orphan(0, hostnames=("app0.example.com",))
        tracker = HealthTracker(
            HealthConfig(
                window=60.0, min_calls=1, failure_ratio=0.5,
                open_duration=60.0, aimd_qps=0,
            )
        )
        tracker.service("globalaccelerator").record(OUTCOME_SERVER_ERROR)
        assert tracker.is_open("globalaccelerator")
        gc = world.gc(health=tracker, grace_sweeps=1)
        report = gc.sweep_once()
        assert "globalaccelerator" in report["skipped_circuit_open"]
        assert report["deleted"]["accelerators"] == 0
        assert len(world.aws.all_accelerator_arns()) == 1

        tracker.service("route53").record(OUTCOME_SERVER_ERROR)
        report = gc.sweep_once()
        assert "route53" in report["skipped_circuit_open"]
        assert report["deleted"]["records"] == 0

    def test_unparseable_owner_tag_is_never_deleted(self, world):
        world.aws.create_accelerator(
            "mystery", "IPV4", True,
            [
                Tag(MANAGED_TAG_KEY, "true"),
                Tag(CLUSTER_TAG_KEY, "default"),
                Tag(OWNER_TAG_KEY, "not-an-owner-identity"),
            ],
        )
        gc = world.gc(grace_sweeps=1)
        for _ in range(3):
            report = gc.sweep_once()
            assert report["candidates"]["accelerators"] == 0
        assert len(world.aws.all_accelerator_arns()) == 1

    def test_unknown_resource_kind_is_never_deleted(self, world):
        world.aws.create_accelerator(
            "cron", "IPV4", True,
            [
                Tag(MANAGED_TAG_KEY, "true"),
                Tag(CLUSTER_TAG_KEY, "default"),
                Tag(OWNER_TAG_KEY, "cronjob/default/mystery"),
            ],
        )
        gc = world.gc(grace_sweeps=1)
        gc.sweep_once()
        gc.sweep_once()
        assert len(world.aws.all_accelerator_arns()) == 1

    def test_foreign_cluster_resources_are_invisible(self, world):
        # another cluster's accelerator + records share the AWS account
        world.aws.add_load_balancer("lb9", NLB_REGION, nlb_hostname(9))
        svc = make_lb_service(name="theirs", hostname=nlb_hostname(9))
        world.driver.ensure_global_accelerator_for_service(
            svc, svc.status.load_balancer.ingress[0], "other-cluster", "lb9", NLB_REGION
        )
        world.driver.ensure_route53_for_service(
            svc, svc.status.load_balancer.ingress[0],
            ["their.example.com"], "other-cluster",
        )
        gc = world.gc(grace_sweeps=1)  # cluster_name=default
        for _ in range(3):
            report = gc.sweep_once()
            assert report["candidates"] == {"accelerators": 0, "records": 0}
        assert len(world.aws.all_accelerator_arns()) == 1
        assert ("their.example.com.", "A") in world.record_names()


class TestObservability:
    def test_status_carries_totals_and_last_sweep(self, world):
        world.make_orphan(0)
        gc = world.gc(grace_sweeps=1)
        gc.sweep_once()
        status = gc.status()
        assert status["enabled"] is True
        assert status["sweeps_total"] == 1
        assert status["deleted_total"] == 1
        assert status["last_sweep"]["deleted"]["accelerators"] == 1
        for key in ("grace_sweeps", "max_deletes", "dry_run", "interval"):
            assert key in status

    def test_healthz_surfaces_gc_counters(self, world):
        world.make_orphan(0)
        gc = world.gc(grace_sweeps=1, dry_run=True)
        gc.sweep_once()
        server = make_health_server(0, gc_status=gc.status)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{server.server_address[1]}/healthz"
            with urllib.request.urlopen(url, timeout=5) as response:
                body = json.loads(response.read())
            assert body["gc"]["enabled"] is True
            assert body["gc"]["dry_run"] is True
            assert body["gc"]["last_sweep"]["would_delete"] == 1
        finally:
            server.shutdown()
            server.server_close()

    def test_disabled_gc_reports_disabled_on_healthz(self):
        server = make_health_server(0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{server.server_address[1]}/healthz"
            with urllib.request.urlopen(url, timeout=5) as response:
                body = json.loads(response.read())
            assert body["gc"] == {"enabled": False}
        finally:
            server.shutdown()
            server.server_close()


class TestManagerWiring:
    def test_manager_runs_the_sweeper_and_mops_orphans(self, world):
        """End-to-end through the manager: an orphan left by a dead
        generation is swept by a manager whose config enables GC —
        while a live owner's chain is untouched."""
        from agac_tpu.manager import ControllerConfig, Manager

        orphan_arn, _ = world.make_orphan(0, hostnames=("app0.example.com",))
        world.aws.add_load_balancer("lb1", NLB_REGION, nlb_hostname(1))
        world.cluster.create(
            "Service",
            make_lb_service(
                name="alive",
                hostname=nlb_hostname(1),
                annotations={apis.ROUTE53_HOSTNAME_ANNOTATION: "live.example.com"},
            ),
        )
        stop = threading.Event()
        config = ControllerConfig(
            garbage_collector=GarbageCollectorConfig(
                interval=0.05, grace_sweeps=2, max_deletes=10
            )
        )
        manager = Manager(resync_period=0.3)
        manager.run(
            world.cluster, config, stop,
            cloud_factory=lambda region: AWSDriver(
                world.aws, world.aws, world.aws,
                poll_interval=0.01, poll_timeout=2.0,
                lb_not_active_retry=0.05, accelerator_missing_retry=0.05,
            ),
            block=False,
        )
        try:
            assert manager.gc is not None
            assert wait_until(
                lambda: orphan_arn not in world.aws.all_accelerator_arns(),
                timeout=10.0,
            ), manager.gc_status()
            # the live service converged and survived every sweep
            assert wait_until(
                lambda: ("live.example.com.", "A") in world.record_names(),
                timeout=10.0,
            )
            assert len(world.aws.all_accelerator_arns()) == 1
            assert ("app0.example.com.", "A") not in world.record_names()
            assert manager.gc_status()["deleted_total"] >= 2
        finally:
            stop.set()


def gc_sees_service(world: World, name: str) -> bool:
    informer = world.factory.informer("Service")
    try:
        informer.lister().namespaced("default").get(name)
        return True
    except Exception:
        return False
