"""Reconcile-kernel retry-policy matrix, the spec encoded by the
reference's ``pkg/reconcile/reconcile.go:59-90`` (SURVEY.md §7 stage 1):

| outcome of processing           | queue effect                       |
|---------------------------------|------------------------------------|
| lookup NotFound                 | delete path runs                   |
| lookup other error              | logged, NO requeue                 |
| process raises                  | rate-limited requeue               |
| process raises NoRetryError     | logged, NO requeue                 |
| Result(requeue_after=d)         | forget + add_after(d)              |
| Result(requeue=True)            | rate-limited requeue               |
| Result()                        | forget                             |
"""

import dataclasses

import pytest

from agac_tpu.errors import NoRetryError, NotFoundError
from agac_tpu.reconcile import Result, process_next_work_item
from agac_tpu.reconcile.workqueue import RateLimitingQueue


class RecordingQueue(RateLimitingQueue):
    """A real queue that also records the kernel's policy calls."""

    def __init__(self):
        super().__init__(name="recording")
        self.calls = []

    def add_rate_limited(self, item, reason=""):
        self.calls.append(("add_rate_limited", item))
        super().add_rate_limited(item, reason=reason)

    def add_after(self, item, delay, reason=""):
        self.calls.append(("add_after", item, delay))
        super().add_after(item, delay, reason=reason)

    def forget(self, item):
        self.calls.append(("forget", item))
        super().forget(item)


@dataclasses.dataclass
class Obj:
    name: str
    labels: dict


@pytest.fixture
def queue():
    q = RecordingQueue()
    yield q
    q.shutdown()


def run_one(queue, key_to_obj, process_delete, process_create_or_update):
    assert process_next_work_item(queue, key_to_obj, process_delete, process_create_or_update)


def test_not_found_dispatches_delete(queue):
    deleted = []
    queue.add("ns/gone")

    def key_to_obj(key):
        raise NotFoundError("Service", key)

    def process_delete(key):
        deleted.append(key)
        return Result()

    run_one(queue, key_to_obj, process_delete, lambda obj: pytest.fail("wrong path"))
    assert deleted == ["ns/gone"]
    assert ("forget", "ns/gone") in queue.calls


def test_lookup_error_is_not_requeued(queue):
    queue.add("ns/broken")

    def key_to_obj(key):
        raise RuntimeError("store exploded")

    run_one(queue, key_to_obj, lambda k: pytest.fail(), lambda o: pytest.fail())
    assert not any(c[0] == "add_rate_limited" for c in queue.calls)


def test_success_forgets(queue):
    queue.add("ns/ok")
    run_one(queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), lambda obj: Result())
    assert queue.calls == [("forget", "ns/ok")]
    assert len(queue) == 0


def test_error_requeues_rate_limited(queue):
    queue.add("ns/fail")

    def process(obj):
        raise RuntimeError("aws is down")

    run_one(queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), process)
    assert any(c[0] == "add_rate_limited" for c in queue.calls)
    assert not any(c[0] == "forget" for c in queue.calls)
    # and the item really comes back
    item, shutdown = queue.get(timeout=2)
    assert (item, shutdown) == ("ns/fail", False)


def test_no_retry_error_not_requeued(queue):
    queue.add("ns/bad")

    def process(obj):
        raise NoRetryError("object is not Service")

    run_one(queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), process)
    assert not any(c[0] == "add_rate_limited" for c in queue.calls)


def test_wrapped_no_retry_error_not_requeued(queue):
    queue.add("ns/bad")

    def process(obj):
        try:
            raise NoRetryError("inner")
        except NoRetryError as inner:
            raise RuntimeError("outer") from inner

    run_one(queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), process)
    assert not any(c[0] == "add_rate_limited" for c in queue.calls)


def test_requeue_after_forgets_then_delays(queue):
    queue.add("ns/wait")
    run_one(
        queue,
        lambda k: Obj(k, {}),
        lambda k: pytest.fail(),
        lambda obj: Result(requeue=True, requeue_after=0.05),
    )
    assert ("forget", "ns/wait") in queue.calls
    assert any(c[0] == "add_after" and c[2] == 0.05 for c in queue.calls)
    item, shutdown = queue.get(timeout=2)
    assert (item, shutdown) == ("ns/wait", False)


def test_requeue_true_rate_limits(queue):
    queue.add("ns/again")
    run_one(queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), lambda obj: Result(requeue=True))
    assert any(c[0] == "add_rate_limited" for c in queue.calls)


def test_process_receives_deep_copy(queue):
    original = Obj("ns/x", {"k": "v"})
    queue.add("ns/x")

    def process(obj):
        assert obj == original
        assert obj is not original
        obj.labels["k"] = "mutated"  # must not leak into the store
        return Result()

    run_one(queue, lambda k: original, lambda k: pytest.fail(), process)
    assert original.labels == {"k": "v"}


def test_non_string_key_forgotten(queue):
    queue.add(42)
    run_one(queue, lambda k: pytest.fail(), lambda k: pytest.fail(), lambda o: pytest.fail())
    assert ("forget", 42) in queue.calls


def test_shutdown_returns_false(queue):
    queue.shutdown()
    assert not process_next_work_item(
        queue, lambda k: None, lambda k: Result(), lambda o: Result()
    )


def test_delete_path_error_requeues(queue):
    queue.add("ns/gone")

    def key_to_obj(key):
        raise NotFoundError("Service", key)

    def process_delete(key):
        raise RuntimeError("cloud cleanup failed")

    run_one(queue, key_to_obj, process_delete, lambda o: pytest.fail())
    assert any(c[0] == "add_rate_limited" for c in queue.calls)


class TestOnSyncErrorHook:
    """The observability hook (VERDICT r1 #6): fired after the retry
    policy with (key, err, num_requeues, permanent); contained; silent
    on success."""

    def test_retryable_error_reports_requeues(self, queue):
        seen = []
        queue.add("ns/fail")

        def process(obj):
            raise RuntimeError("aws is down")

        assert process_next_work_item(
            queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), process,
            lambda *a: seen.append(a),
        )
        assert len(seen) == 1
        key, err, requeues, permanent = seen[0]
        assert key == "ns/fail" and "aws is down" in str(err)
        assert requeues == 1 and permanent is False

    def test_no_retry_error_reports_permanent(self, queue):
        seen = []
        queue.add("ns/bad")

        def process(obj):
            raise NoRetryError("config error")

        assert process_next_work_item(
            queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), process,
            lambda *a: seen.append(a),
        )
        assert seen[0][3] is True
        assert not any(c[0] == "add_rate_limited" for c in queue.calls)

    def test_success_fires_with_none_error(self, queue):
        """Successful syncs notify with err=None so streak-tracking
        hooks (the SyncFailing warner) can reset their counts."""
        seen = []
        queue.add("ns/ok")
        assert process_next_work_item(
            queue, lambda k: Obj(k, {}), lambda k: pytest.fail(),
            lambda obj: Result(), lambda *a: seen.append(a),
        )
        assert seen == [("ns/ok", None, 0, False)]

    def test_hook_exception_is_contained(self, queue):
        queue.add("ns/fail")

        def process(obj):
            raise RuntimeError("boom")

        def bad_hook(*a):
            raise ValueError("hook bug")

        # neither the worker nor the retry policy is disturbed
        assert process_next_work_item(
            queue, lambda k: Obj(k, {}), lambda k: pytest.fail(), process, bad_hook
        )
        assert any(c[0] == "add_rate_limited" for c in queue.calls)


class TestSyncDurationObserver:
    """The process-global metrics seam: observers see (key, seconds,
    error) for every completed sync pass (the reference only logs the
    duration at v4, ``reconcile.go:44-47``)."""

    def test_observer_sees_success_and_failure(self, queue):
        from agac_tpu.reconcile import (
            add_sync_duration_observer,
            remove_sync_duration_observer,
        )

        seen = []
        observer = lambda key, seconds, err: seen.append((key, seconds, err))
        add_sync_duration_observer(observer)
        try:
            queue.add("ns/ok")
            process_next_work_item(
                queue, lambda k: Obj(k, {}), lambda k: pytest.fail(),
                lambda obj: Result(),
            )
            boom = RuntimeError("boom")
            queue.add("ns/fail")
            process_next_work_item(
                queue, lambda k: Obj(k, {}), lambda k: pytest.fail(),
                lambda obj: (_ for _ in ()).throw(boom),
            )
        finally:
            remove_sync_duration_observer(observer)
        assert [s[0] for s in seen] == ["ns/ok", "ns/fail"]
        assert all(s[1] >= 0 for s in seen)
        assert seen[0][2] is None and seen[1][2] is boom

    def test_observer_exception_contained_and_removal_idempotent(self, queue):
        from agac_tpu.reconcile import (
            add_sync_duration_observer,
            remove_sync_duration_observer,
        )

        def bad_observer(key, seconds, err):
            raise ValueError("observer bug")

        add_sync_duration_observer(bad_observer)
        try:
            queue.add("ns/ok")
            assert process_next_work_item(
                queue, lambda k: Obj(k, {}), lambda k: pytest.fail(),
                lambda obj: Result(),
            )
        finally:
            remove_sync_duration_observer(bad_observer)
        remove_sync_duration_observer(bad_observer)  # no-op, no raise
