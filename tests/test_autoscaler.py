"""Unit tier for the SLO-driven shard autoscaler (ISSUE 13):
``agac_tpu/autoscaler/`` — signal collection (``signals.py``), the
railed scale policy as a pure fake-clock state machine (``policy.py``),
and the collect→evaluate→record→act loop (``loop.py``).  Every rail
gets a direct test here; the closed-loop behavior (load wave → resize →
restored SLO) is proven by the sim tier (``sim/fuzz.py --scenario
autoscale``) and tests/test_sharding_sim.py.
"""

from __future__ import annotations

import pytest

from agac_tpu.autoscaler import (
    ACTION_HOLD,
    ACTION_IN,
    ACTION_OUT,
    RAIL_AT_MAX,
    RAIL_AT_MIN,
    RAIL_COOLDOWN_IN,
    RAIL_COOLDOWN_OUT,
    RAIL_DISABLED,
    RAIL_EXECUTE_ERROR,
    RAIL_OBSERVE_ONLY,
    RAIL_TRANSITION,
    REASON_AGE,
    REASON_BURN,
    REASON_HEADROOM,
    REASON_STEADY,
    AutoscalerLoop,
    ScalePolicy,
    ScalePolicyConfig,
    ScaleSignals,
    SignalSnapshot,
    services_for_controllers,
)
from agac_tpu.observability.metrics import MetricsRegistry, parse_text
from agac_tpu.observability.recorder import FlightRecorder

GA_OBJ = "ga_converge_p99"
R53_OBJ = "route53_converge_p99"


class FakeClock:
    def __init__(self, now: float = 0.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def snap(
    time=0.0,
    shard_count=2,
    resize_state="stable",
    handoff_pending=0,
    burn=None,
    objective_services=None,
    oldest_age=0.0,
    open_circuits=(),
    **kw,
):
    return SignalSnapshot(
        time=time,
        shard_count=shard_count,
        resize_state=resize_state,
        handoff_pending=handoff_pending,
        burn=burn if burn is not None else {},
        objective_services=(
            objective_services
            if objective_services is not None
            else {GA_OBJ: frozenset(["globalaccelerator"])}
        ),
        oldest_age=oldest_age,
        open_circuits=frozenset(open_circuits),
        **kw,
    )


def burning(rate=2.0):
    """Both-window burn at ``rate`` on the GA objective."""
    return {GA_OBJ: {300.0: rate, 3600.0: rate}}


def cool():
    return {GA_OBJ: {300.0: 0.0, 3600.0: 0.0}}


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


class TestScalePolicyConfig:
    def test_defaults_are_valid(self):
        cfg = ScalePolicyConfig()
        assert cfg.min_shards == 1 and cfg.max_shards == 8

    def test_min_shards_must_be_positive(self):
        with pytest.raises(ValueError):
            ScalePolicyConfig(min_shards=0)

    def test_max_must_not_be_below_min(self):
        with pytest.raises(ValueError):
            ScalePolicyConfig(min_shards=4, max_shards=2)

    def test_streaks_must_be_positive(self):
        with pytest.raises(ValueError):
            ScalePolicyConfig(age_growth_evals=0)
        with pytest.raises(ValueError):
            ScalePolicyConfig(headroom_evals=0)


# ---------------------------------------------------------------------------
# scale-out evidence
# ---------------------------------------------------------------------------


class TestBurnEvidence:
    def test_both_window_burn_scales_out(self):
        policy = ScalePolicy(ScalePolicyConfig(min_shards=1, max_shards=8))
        d = policy.evaluate(snap(time=10.0, burn=burning()))
        assert d.action == ACTION_OUT and d.reason == REASON_BURN
        assert d.executed and d.rails == ()
        assert d.target_shards == 4  # one doubling of 2

    def test_single_window_burn_holds(self):
        # the multi-window rule: a short spike with a cool long window
        # (or stale long-window burn with a recovered short window) is
        # not sustained evidence
        policy = ScalePolicy()
        d = policy.evaluate(
            snap(burn={GA_OBJ: {300.0: 5.0, 3600.0: 0.2}})
        )
        assert d.action == ACTION_HOLD and d.reason == REASON_STEADY
        assert not d.executed

    def test_burn_exactly_at_threshold_trips(self):
        policy = ScalePolicy(ScalePolicyConfig(burn_threshold=1.0))
        d = policy.evaluate(snap(burn=burning(1.0)))
        assert d.action == ACTION_OUT

    def test_empty_burn_windows_are_not_evidence(self):
        policy = ScalePolicy()
        d = policy.evaluate(snap(burn={GA_OBJ: {}}))
        assert d.action == ACTION_HOLD

    def test_max_step_is_one_doubling(self):
        policy = ScalePolicy(ScalePolicyConfig(max_shards=16))
        d = policy.evaluate(snap(shard_count=2, burn=burning()))
        assert d.target_shards == 4  # not 16

    def test_doubling_clamps_to_max(self):
        policy = ScalePolicy(ScalePolicyConfig(max_shards=6))
        d = policy.evaluate(snap(shard_count=4, burn=burning()))
        assert d.target_shards == 6 and d.executed


# ---------------------------------------------------------------------------
# age-growth evidence
# ---------------------------------------------------------------------------


class TestAgeGrowthEvidence:
    CFG = ScalePolicyConfig(age_growth_evals=3, age_floor_seconds=60.0)

    def test_growing_age_above_floor_scales_out_after_streak(self):
        policy = ScalePolicy(self.CFG)
        ages = [70.0, 90.0, 110.0, 130.0]
        decisions = [
            policy.evaluate(snap(time=30.0 * i, oldest_age=age, burn=cool()))
            for i, age in enumerate(ages)
        ]
        # first eval has no previous age to compare against
        assert [d.action for d in decisions[:3]] == [ACTION_HOLD] * 3
        assert decisions[3].action == ACTION_OUT
        assert decisions[3].reason == REASON_AGE

    def test_age_below_floor_never_counts(self):
        policy = ScalePolicy(self.CFG)
        for i, age in enumerate([10.0, 20.0, 30.0, 40.0, 50.0]):
            d = policy.evaluate(snap(time=30.0 * i, oldest_age=age))
        assert d.action == ACTION_HOLD
        assert d.evidence["age_growth_streak"] == 0

    def test_plateau_resets_the_streak(self):
        policy = ScalePolicy(self.CFG)
        for i, age in enumerate([70.0, 90.0, 110.0, 110.0, 130.0]):
            d = policy.evaluate(snap(time=30.0 * i, oldest_age=age))
        # plateau at eval 3 reset the streak; eval 4 restarts at 1
        assert d.action == ACTION_HOLD
        assert d.evidence["age_growth_streak"] == 1

    def test_open_circuit_voids_age_evidence(self):
        policy = ScalePolicy(self.CFG)
        for i, age in enumerate([70.0, 90.0, 110.0, 130.0, 150.0]):
            d = policy.evaluate(
                snap(
                    time=30.0 * i,
                    oldest_age=age,
                    open_circuits=["globalaccelerator"],
                )
            )
        assert d.action == ACTION_HOLD
        assert d.evidence["age_growth_streak"] == 0


# ---------------------------------------------------------------------------
# scale-in evidence
# ---------------------------------------------------------------------------


class TestHeadroomEvidence:
    CFG = ScalePolicyConfig(
        min_shards=1, headroom_evals=4, headroom_burn=0.25
    )

    def test_sustained_headroom_scales_in(self):
        policy = ScalePolicy(self.CFG)
        for i in range(4):
            d = policy.evaluate(
                snap(time=30.0 * i, shard_count=4, burn=cool())
            )
        assert d.action == ACTION_IN and d.reason == REASON_HEADROOM
        assert d.executed and d.target_shards == 2  # one halving

    def test_warm_burn_resets_the_streak(self):
        policy = ScalePolicy(self.CFG)
        burns = [cool(), cool(), cool(), burning(0.5), cool()]
        for i, b in enumerate(burns):
            d = policy.evaluate(snap(time=30.0 * i, shard_count=4, burn=b))
        assert d.action == ACTION_HOLD
        assert d.evidence["headroom_streak"] == 1

    def test_old_backlog_blocks_headroom(self):
        policy = ScalePolicy(self.CFG)
        for i in range(6):
            d = policy.evaluate(
                snap(
                    time=30.0 * i,
                    shard_count=4,
                    burn=cool(),
                    oldest_age=200.0,
                )
            )
        assert d.action == ACTION_HOLD

    def test_halving_clamps_to_min(self):
        policy = ScalePolicy(ScalePolicyConfig(min_shards=3, headroom_evals=1))
        d = policy.evaluate(snap(shard_count=4, burn=cool()))
        assert d.action == ACTION_IN and d.target_shards == 3


# ---------------------------------------------------------------------------
# brownout exclusion
# ---------------------------------------------------------------------------


class TestBrownoutExclusion:
    def test_open_circuit_excludes_objective_from_burn(self):
        policy = ScalePolicy()
        d = policy.evaluate(
            snap(burn=burning(), open_circuits=["globalaccelerator"])
        )
        assert d.action == ACTION_HOLD
        assert d.evidence["excluded_objectives"] == [GA_OBJ]
        assert d.evidence["tripped_objectives"] == []

    def test_unrelated_circuit_does_not_exclude(self):
        policy = ScalePolicy()
        d = policy.evaluate(snap(burn=burning(), open_circuits=["route53"]))
        assert d.action == ACTION_OUT
        assert d.evidence["excluded_objectives"] == []

    def test_other_objectives_still_count_during_a_brownout(self):
        policy = ScalePolicy()
        d = policy.evaluate(
            snap(
                burn={
                    GA_OBJ: {300.0: 3.0, 3600.0: 3.0},
                    R53_OBJ: {300.0: 2.0, 3600.0: 2.0},
                },
                objective_services={
                    GA_OBJ: frozenset(["globalaccelerator"]),
                    R53_OBJ: frozenset(["route53"]),
                },
                open_circuits=["globalaccelerator"],
            )
        )
        assert d.action == ACTION_OUT
        assert d.evidence["tripped_objectives"] == [R53_OBJ]
        assert d.evidence["excluded_objectives"] == [GA_OBJ]

    def test_exclusion_holds_after_the_circuit_recloses(self):
        # the outage's wedged journeys burn the windows AFTER the
        # restore — the hold keeps the echo from scaling the fleet
        policy = ScalePolicy(ScalePolicyConfig(brownout_hold_seconds=300.0))
        policy.evaluate(
            snap(time=0.0, open_circuits=["globalaccelerator"], burn=cool())
        )
        d = policy.evaluate(snap(time=200.0, burn=burning()))
        assert d.action == ACTION_HOLD
        assert d.evidence["recently_open_circuits"] == ["globalaccelerator"]
        assert d.evidence["excluded_objectives"] == [GA_OBJ]

    def test_hold_expires(self):
        policy = ScalePolicy(ScalePolicyConfig(brownout_hold_seconds=300.0))
        policy.evaluate(
            snap(time=0.0, open_circuits=["globalaccelerator"], burn=cool())
        )
        d = policy.evaluate(snap(time=301.0, burn=burning()))
        assert d.action == ACTION_OUT
        assert d.evidence["recently_open_circuits"] == []

    def test_reopening_extends_the_hold(self):
        policy = ScalePolicy(ScalePolicyConfig(brownout_hold_seconds=300.0))
        policy.evaluate(
            snap(time=0.0, open_circuits=["globalaccelerator"], burn=cool())
        )
        policy.evaluate(
            snap(time=250.0, open_circuits=["globalaccelerator"], burn=cool())
        )
        d = policy.evaluate(snap(time=400.0, burn=burning()))
        assert d.action == ACTION_HOLD  # held until 250 + 300


# ---------------------------------------------------------------------------
# rails
# ---------------------------------------------------------------------------


class TestRails:
    def test_disabled_rail(self):
        policy = ScalePolicy(ScalePolicyConfig(enabled=False))
        d = policy.evaluate(snap(burn=burning()))
        assert d.action == ACTION_OUT and not d.executed
        assert RAIL_DISABLED in d.rails

    def test_transition_rail_on_resize_state(self):
        policy = ScalePolicy()
        d = policy.evaluate(snap(burn=burning(), resize_state="draining"))
        assert not d.executed and RAIL_TRANSITION in d.rails

    def test_transition_rail_on_pending_handoffs(self):
        policy = ScalePolicy()
        d = policy.evaluate(snap(burn=burning(), handoff_pending=3))
        assert not d.executed and RAIL_TRANSITION in d.rails

    def test_cooldown_out_after_an_executed_resize(self):
        policy = ScalePolicy(ScalePolicyConfig(cooldown_out_seconds=120.0))
        first = policy.evaluate(snap(time=0.0, burn=burning()))
        assert first.executed
        d = policy.evaluate(snap(time=60.0, shard_count=4, burn=burning()))
        assert not d.executed and RAIL_COOLDOWN_OUT in d.rails
        d = policy.evaluate(snap(time=121.0, shard_count=4, burn=burning()))
        assert d.executed and d.target_shards == 8

    def test_cooldown_in_outlasts_cooldown_out(self):
        cfg = ScalePolicyConfig(
            cooldown_out_seconds=120.0,
            cooldown_in_seconds=600.0,
            headroom_evals=1,
        )
        policy = ScalePolicy(cfg)
        assert policy.evaluate(snap(time=0.0, burn=burning())).executed
        # cooled enough for another scale-out, but not for a scale-in
        d = policy.evaluate(snap(time=200.0, shard_count=4, burn=cool()))
        assert d.action == ACTION_IN and not d.executed
        assert RAIL_COOLDOWN_IN in d.rails
        d = policy.evaluate(snap(time=601.0, shard_count=4, burn=cool()))
        assert d.executed

    def test_at_max_rail(self):
        policy = ScalePolicy(ScalePolicyConfig(max_shards=4))
        d = policy.evaluate(snap(shard_count=4, burn=burning()))
        assert d.action == ACTION_OUT and not d.executed
        assert RAIL_AT_MAX in d.rails and d.target_shards == 4

    def test_at_min_rail(self):
        policy = ScalePolicy(
            ScalePolicyConfig(min_shards=2, headroom_evals=1)
        )
        d = policy.evaluate(snap(shard_count=2, burn=cool()))
        assert d.action == ACTION_IN and not d.executed
        assert RAIL_AT_MIN in d.rails

    def test_observe_only_suppresses_a_clean_desire(self):
        policy = ScalePolicy(ScalePolicyConfig(observe_only=True))
        d = policy.evaluate(snap(burn=burning()))
        assert d.action == ACTION_OUT and not d.executed
        assert d.rails == (RAIL_OBSERVE_ONLY,)
        assert d.target_shards == 4  # the recommendation is still real

    def test_observe_only_defers_to_harder_rails(self):
        # when another rail already suppressed the decision, the label
        # should name THAT rail, not observe-only
        policy = ScalePolicy(
            ScalePolicyConfig(observe_only=True, max_shards=2)
        )
        d = policy.evaluate(snap(shard_count=2, burn=burning()))
        assert d.rails == (RAIL_AT_MAX,)

    def test_hold_carries_no_rails(self):
        policy = ScalePolicy(ScalePolicyConfig(enabled=False))
        d = policy.evaluate(snap(burn=cool()))
        assert d.action == ACTION_HOLD and d.rails == ()

    def test_suppressed_decision_does_not_start_cooldown(self):
        policy = ScalePolicy(ScalePolicyConfig(observe_only=True))
        d1 = policy.evaluate(snap(time=0.0, burn=burning()))
        d2 = policy.evaluate(snap(time=30.0, burn=burning()))
        assert d1.rails == d2.rails == (RAIL_OBSERVE_ONLY,)
        assert d2.evidence["since_last_resize_s"] is None


# ---------------------------------------------------------------------------
# state machine bookkeeping
# ---------------------------------------------------------------------------


class TestPolicyState:
    def test_executed_resize_resets_both_streaks(self):
        cfg = ScalePolicyConfig(headroom_evals=3)
        policy = ScalePolicy(cfg)
        for i in range(2):
            policy.evaluate(snap(time=30.0 * i, shard_count=4, burn=cool()))
        d = policy.evaluate(snap(time=60.0, shard_count=4, burn=burning()))
        assert d.executed
        # evidence captured the streak as it stood at this evaluation
        assert d.evidence["headroom_streak"] == 0  # burn broke it
        d = policy.evaluate(snap(time=300.0, shard_count=8, burn=cool()))
        assert d.evidence["headroom_streak"] == 1  # restarted from zero

    def test_evidence_schema(self):
        policy = ScalePolicy()
        d = policy.evaluate(
            snap(
                time=12.3456,
                burn=burning(1.5),
                oldest_age=42.0,
                keys_by_shard={"0": 3, "1": 5},
            )
        )
        ev = d.evidence
        assert ev["burn"] == {GA_OBJ: {"300s": 1.5, "3600s": 1.5}}
        assert ev["tripped_objectives"] == [GA_OBJ]
        assert ev["oldest_unconverged_age_s"] == 42.0
        assert ev["keys_by_shard"] == {"0": 3, "1": 5}
        for key in (
            "burn_threshold", "excluded_objectives", "open_circuits",
            "recently_open_circuits", "age_growth_streak", "headroom_streak",
            "resize_state", "handoff_pending", "since_last_resize_s",
            "cooldown_out_s", "cooldown_in_s", "min_shards", "max_shards",
        ):
            assert key in ev

    def test_to_dict_roundtrips_error(self):
        policy = ScalePolicy()
        d = policy.evaluate(snap(burn=burning()))
        assert "error" not in d.to_dict()
        d.error = "boom"
        assert d.to_dict()["error"] == "boom"


# ---------------------------------------------------------------------------
# signal collection
# ---------------------------------------------------------------------------


class TestServicesForControllers:
    def test_route53_prefix_maps_to_route53(self):
        assert services_for_controllers(
            ["route53-controller-service"]
        ) == frozenset(["route53"])

    def test_everything_else_maps_to_ga(self):
        got = services_for_controllers(
            ["global-accelerator-controller-service", "endpointgroupbinding"]
        )
        assert got == frozenset(["globalaccelerator"])


class TestScaleSignals:
    def test_defaults_without_sources(self):
        clock = FakeClock(77.0)
        s = ScaleSignals(clock=clock).collect()
        assert s.time == 77.0
        assert s.shard_count == 1 and s.resize_state == "stable"
        assert s.burn == {} and s.oldest_age == 0.0
        assert s.open_circuits == frozenset()

    def test_collect_reads_every_source(self):
        class FakeSLO:
            objectives = (
                type(
                    "Obj", (), {
                        "name": GA_OBJ,
                        "controllers": ("global-accelerator-controller-service",),
                    },
                )(),
            )

            @staticmethod
            def burn_snapshot():
                return {GA_OBJ: {300.0: 1.5}}

        class FakeJourney:
            @staticmethod
            def oldest_unconverged_age():
                return 33.0

            @staticmethod
            def inflight():
                return 7

        s = ScaleSignals(
            slo_engine=FakeSLO(),
            journey_tracker=FakeJourney(),
            resize_status=lambda: {
                "shard_count": 4, "state": "draining", "handoff_pending": 2,
            },
            keys_by_shard=lambda: {"0": 9},
            replica_count=lambda: 5,
            open_circuits=lambda: ["route53"],
            clock=FakeClock(5.0),
        ).collect()
        assert s.shard_count == 4 and s.resize_state == "draining"
        assert s.handoff_pending == 2
        assert s.burn == {GA_OBJ: {300.0: 1.5}}
        assert s.objective_services == {
            GA_OBJ: frozenset(["globalaccelerator"])
        }
        assert s.oldest_age == 33.0 and s.inflight == 7
        assert s.keys_by_shard == {"0": 9} and s.replica_count == 5
        assert s.open_circuits == frozenset(["route53"])

    def test_broken_sources_degrade_to_defaults(self):
        def boom():
            raise RuntimeError("lease read raced a CAS")

        s = ScaleSignals(
            resize_status=boom,
            keys_by_shard=boom,
            replica_count=boom,
            open_circuits=boom,
            clock=FakeClock(),
        ).collect()
        assert s.shard_count == 1 and s.resize_state == "stable"
        assert s.keys_by_shard == {} and s.replica_count == 0

    def test_none_shard_count_degrades_to_one(self):
        s = ScaleSignals(
            resize_status=lambda: {"shard_count": None},
            clock=FakeClock(),
        ).collect()
        assert s.shard_count == 1


# ---------------------------------------------------------------------------
# the loop
# ---------------------------------------------------------------------------


def make_loop(policy_cfg=None, burn=None, execute="collect", clock=None):
    clock = clock or FakeClock()
    calls = []
    signals = ScaleSignals(
        resize_status=lambda: {"shard_count": 2, "state": "stable"},
        clock=clock,
    )
    # inject burn by overriding collect-level sources
    if burn is not None:
        base_collect = signals.collect

        def collect():
            s = base_collect()
            s.burn = burn
            s.objective_services = {
                GA_OBJ: frozenset(["globalaccelerator"])
            }
            return s

        signals.collect = collect
    reg = MetricsRegistry()
    recorder = FlightRecorder(capacity=64, clock=clock)
    loop = AutoscalerLoop(
        signals,
        ScalePolicy(policy_cfg or ScalePolicyConfig()),
        execute=calls.append if execute == "collect" else execute,
        registry=reg,
        flight_recorder=recorder,
    )
    return loop, calls, reg, recorder


class TestAutoscalerLoop:
    def test_tick_executes_and_records(self):
        loop, calls, reg, recorder = make_loop(burn=burning())
        d = loop.tick()
        assert d.executed and calls == [4]
        assert loop.ticks == 1 and loop.executed_total == 1
        samples = parse_text(reg.render())
        assert samples["agac_autoscaler_target_shards"] == 4.0
        assert samples[
            'agac_autoscaler_decisions_total{action="scale-out",reason="burn"}'
        ] == 1
        entries = recorder.dump()
        assert len(entries) == 1 and entries[0]["kind"] == "autoscale"
        assert entries[0]["action"] == ACTION_OUT
        assert entries[0]["evidence"]["tripped_objectives"] == [GA_OBJ]

    def test_every_decision_is_flight_recorded(self):
        loop, _calls, _reg, recorder = make_loop()  # no burn → holds
        for _ in range(5):
            loop.tick()
        assert recorder.recorded_total == 5
        assert all(e["action"] == ACTION_HOLD for e in recorder.dump())

    def test_suppression_metric_carries_the_rail(self):
        loop, calls, reg, _rec = make_loop(
            policy_cfg=ScalePolicyConfig(observe_only=True), burn=burning()
        )
        loop.tick()
        assert calls == []
        samples = parse_text(reg.render())
        assert samples[
            'agac_autoscaler_suppressed_total{rail="observe-only"}'
        ] == 1

    def test_observe_only_never_calls_execute(self):
        def forbidden(_target):
            raise AssertionError("observe-only must never resize")

        loop, _calls, _reg, recorder = make_loop(
            policy_cfg=ScalePolicyConfig(observe_only=True),
            burn=burning(),
            execute=forbidden,
        )
        for _ in range(3):
            d = loop.tick()
            assert not d.executed
        assert loop.executed_total == 0
        assert recorder.recorded_total == 3

    def test_execute_error_is_captured_not_raised(self):
        def boom(_target):
            raise RuntimeError("lease CAS lost")

        loop, _calls, reg, recorder = make_loop(burn=burning(), execute=boom)
        d = loop.tick()
        assert not d.executed
        assert RAIL_EXECUTE_ERROR in d.rails
        assert d.error == "lease CAS lost"
        assert loop.executed_total == 0
        entry = recorder.dump()[0]
        assert entry["error"] == "lease CAS lost"
        samples = parse_text(reg.render())
        assert samples[
            'agac_autoscaler_suppressed_total{rail="execute-error"}'
        ] == 1

    def test_failed_execute_still_starts_the_cooldown(self):
        # a persistently failing resize must not hot-loop the executor
        clock = FakeClock()
        loop, _calls, _reg, _rec = make_loop(
            burn=burning(),
            execute=lambda _t: (_ for _ in ()).throw(RuntimeError("down")),
            clock=clock,
        )
        loop.tick()
        clock.advance(30.0)
        d = loop.tick()
        assert RAIL_COOLDOWN_OUT in d.rails

    def test_missing_executor_is_an_execute_error(self):
        loop, _calls, _reg, _rec = make_loop(burn=burning(), execute=None)
        d = loop.tick()
        assert not d.executed and RAIL_EXECUTE_ERROR in d.rails

    def test_status_shape(self):
        loop, _calls, _reg, _rec = make_loop(burn=burning())
        status = loop.status()
        assert status["evaluations"] == 0 and "last_decision" not in status
        loop.tick()
        status = loop.status()
        assert status["enabled"] is True
        assert status["observe_only"] is False
        assert status["evaluations"] == 1
        assert status["executed_total"] == 1
        last = status["last_decision"]
        assert last["action"] == ACTION_OUT and last["executed"] is True
        assert last["target_shards"] == 4

    def test_history_is_bounded_and_ordered(self):
        clock = FakeClock()
        loop, _calls, _reg, _rec = make_loop(clock=clock)
        loop._history = type(loop._history)(maxlen=3)
        for _ in range(5):
            loop.tick()
            clock.advance(30.0)
        hist = loop.history()
        assert len(hist) == 3
        times = [h["time"] for h in hist]
        assert times == sorted(times)
        assert loop.history(limit=1)[0]["time"] == times[-1]
