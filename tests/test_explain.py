"""Unit tier for the convergence explain plane (ISSUE 15):
``agac_tpu/observability/explain.py`` — one test per verdict in the
closed catalog, causal-timeline assembly order, fleet-merge
owner/non-owner resolution, the O(1)-per-key lookup micro-assert, and
the ``agac_explain_blocked`` gauge exposition round-trip.  The live
wiring (manager endpoint, reconcile reason threading, SIGTERM table)
is covered by tests/test_profiling.py, tests/test_observability.py
and the sim explain oracle.
"""

from __future__ import annotations

import pytest

from agac_tpu.errors import NotFoundError
from agac_tpu.observability import explain, journey
from agac_tpu.observability.metrics import MetricsRegistry, parse_text
from agac_tpu.observability.recorder import FlightRecorder
from agac_tpu.reconcile.pending import PendingSettleTable, SettleWait
from agac_tpu.reconcile.workqueue import RateLimitingQueue

SVC = "global-accelerator-controller-service"
ING = "global-accelerator-controller-ingress"
KEY = "default/app"


class FakeClock:
    def __init__(self, now: float = 100.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


class FakeHealth:
    def open_services(self):
        return ["globalaccelerator"]


class FakeShardFilter:
    """The two ownership shapes membership.ShardFilter.explain_key
    can disclaim a key with."""

    all_shards = False

    def __init__(self, answer):
        self.answer = answer

    def explain_key(self, key):
        return dict(self.answer)


def make_engine(clock=None, **kwargs):
    clock = clock or FakeClock()
    reg = MetricsRegistry()
    journeys = journey.JourneyTracker(registry=reg, clock=clock)
    queue = RateLimitingQueue(name="svc", clock=clock, metrics_registry=reg)
    engine = explain.ExplainEngine(
        journeys=journeys, clock=clock, identity="replica-0", **kwargs
    )
    obj = object()
    engine.register_worker(SVC, queue, lambda key: obj, managed=lambda o: True)
    return engine, journeys, queue, clock, reg


# ---------------------------------------------------------------------------
# one test per verdict
# ---------------------------------------------------------------------------


class TestVerdicts:
    def test_converged(self):
        engine, _, _, _, _ = make_engine()
        answer = engine.classify(SVC, KEY)
        assert answer["verdict"] == explain.VERDICT_CONVERGED

    def test_in_flight_queued(self):
        engine, journeys, queue, _, _ = make_engine()
        journeys.observe_enqueued(SVC, KEY)
        queue.add(KEY)
        answer = engine.classify(SVC, KEY)
        assert answer["verdict"] == explain.VERDICT_IN_FLIGHT
        assert answer["detail"]["queue"] == "ready-or-processing"

    def test_in_flight_between_queue_moves(self):
        engine, journeys, _, _, _ = make_engine()
        journeys.observe_enqueued(SVC, KEY)
        assert engine.classify(SVC, KEY)["verdict"] == explain.VERDICT_IN_FLIGHT

    def test_in_flight_scheduled_recheck(self):
        # a retry_after hint carries reason="in-flight": forward
        # progress on the AWS side, not an error backoff
        engine, journeys, queue, _, _ = make_engine()
        journeys.observe_enqueued(SVC, KEY)
        queue.add_after(KEY, 30.0, reason="in-flight")
        assert engine.classify(SVC, KEY)["verdict"] == explain.VERDICT_IN_FLIGHT

    def test_backoff(self):
        engine, journeys, queue, _, _ = make_engine()
        journeys.observe_enqueued(SVC, KEY)
        queue.add_rate_limited(KEY, reason="backoff")
        answer = engine.classify(SVC, KEY)
        assert answer["verdict"] == explain.VERDICT_BACKOFF
        assert answer["detail"]["delayed"]["requeues"] == 1
        assert answer["detail"]["delayed"]["eta_s"] >= 0

    def test_backoff_is_the_unreasoned_delay_default(self):
        engine, journeys, queue, _, _ = make_engine()
        journeys.observe_enqueued(SVC, KEY)
        queue.add_after(KEY, 12.0)
        assert engine.classify(SVC, KEY)["verdict"] == explain.VERDICT_BACKOFF

    def test_circuit_open(self):
        engine, journeys, queue, _, _ = make_engine(health=FakeHealth())
        journeys.observe_enqueued(SVC, KEY)
        queue.add_after(KEY, 15.0, reason="circuit-open")
        answer = engine.classify(SVC, KEY)
        assert answer["verdict"] == explain.VERDICT_CIRCUIT_OPEN
        assert answer["detail"]["open_circuits"] == ["globalaccelerator"]

    def test_quota_paced(self):
        engine, journeys, queue, _, _ = make_engine()
        journeys.observe_enqueued(SVC, KEY)
        queue.add_after(KEY, 5.0, reason="quota-paced")
        assert engine.classify(SVC, KEY)["verdict"] == explain.VERDICT_QUOTA_PACED

    def test_parked_settle(self):
        clock = FakeClock()
        table = PendingSettleTable(clock=clock, registry=MetricsRegistry())
        engine, journeys, queue, _, _ = make_engine(
            clock=clock, settle_table=table
        )
        journeys.observe_enqueued(SVC, KEY)
        table.park(
            KEY, queue, SettleWait("ga-accelerator", "arn:x", timeout=180.0),
            controller=SVC, reason="parked-settle",
        )
        clock.advance(30.0)
        answer = engine.classify(SVC, KEY)
        assert answer["verdict"] == explain.VERDICT_PARKED_SETTLE
        parked = answer["detail"]["parked"]
        assert parked["group"] == "ga-accelerator"
        assert parked["parked_for_s"] == pytest.approx(30.0)
        assert parked["deadline_in_s"] == pytest.approx(150.0)

    def test_shed(self):
        engine, journeys, _, _, _ = make_engine(slo_shedding=lambda: True)
        journeys.observe_enqueued(SVC, KEY)
        assert engine.classify(SVC, KEY)["verdict"] == explain.VERDICT_SHED

    def test_not_owner(self):
        engine, _, _, _, _ = make_engine(
            shard_filter=FakeShardFilter(
                {"owned": False, "shard": 3, "moving": False}
            ),
        )
        answer = engine.classify(SVC, KEY)
        assert answer["verdict"] == explain.VERDICT_NOT_OWNER
        assert answer["detail"]["shard"] == 3

    def test_unowned_resize(self):
        engine, _, _, _, _ = make_engine(
            shard_filter=FakeShardFilter({
                "owned": False, "shard": 1, "target_shard": 3,
                "moving": True, "drained_here": True, "adopting_here": False,
            }),
            resize_status=lambda: {"epoch": 7, "state": "transitioning"},
        )
        answer = engine.classify(SVC, KEY)
        assert answer["verdict"] == explain.VERDICT_UNOWNED_RESIZE
        assert answer["detail"]["ring_epoch"] == 7
        assert answer["detail"]["resize_state"] == "transitioning"

    def test_informer_unsynced(self):
        engine, _, _, _, _ = make_engine(informers_synced=lambda: False)
        assert (
            engine.classify(SVC, KEY)["verdict"]
            == explain.VERDICT_INFORMER_UNSYNCED
        )

    def test_not_managed(self):
        engine, _, _, _, _ = make_engine()
        engine.register_worker(
            ING, RateLimitingQueue(name="ing", metrics_registry=MetricsRegistry()),
            lambda key: object(), managed=lambda o: False,
        )
        assert engine.classify(ING, KEY)["verdict"] == explain.VERDICT_NOT_MANAGED

    def test_deleted(self):
        engine, _, _, _, _ = make_engine()

        def gone(key):
            raise NotFoundError(f"no such object {key}")

        engine.register_worker(ING, None, gone, managed=None)
        assert engine.classify(ING, KEY)["verdict"] == explain.VERDICT_DELETED

    def test_never_unknown(self):
        # the catalog is closed: every classification lands in it
        engine, journeys, queue, _, _ = make_engine()
        journeys.observe_enqueued(SVC, KEY)
        queue.add_after(KEY, 1.0, reason="in-flight")
        for controller in (SVC, "never-registered"):
            verdict = engine.classify(controller, KEY)["verdict"]
            assert verdict in explain.VERDICTS
            assert verdict != "unknown"


# ---------------------------------------------------------------------------
# the envelope + priority
# ---------------------------------------------------------------------------


class TestEnvelope:
    def test_summary_is_most_blocking_across_controllers(self):
        engine, journeys, queue, _, _ = make_engine()
        # SVC converged; ING circuit-blocked → summary circuit-open
        ing_queue = RateLimitingQueue(name="ing", metrics_registry=MetricsRegistry())
        engine.register_worker(ING, ing_queue, lambda key: object(), managed=None)
        journeys.observe_enqueued(ING, KEY)
        ing_queue.add_after(KEY, 15.0, reason="circuit-open")
        answer = engine.explain(KEY)
        assert answer["verdict"] == explain.VERDICT_CIRCUIT_OPEN
        assert set(answer["controllers"]) == {SVC, ING}

    def test_converged_outranks_another_controllers_not_managed(self):
        # one controller converged it, another's predicate rejects it:
        # the object IS converged
        engine, _, _, _, _ = make_engine()
        engine.register_worker(
            ING, None, lambda key: object(), managed=lambda o: False
        )
        assert engine.explain(KEY)["verdict"] == explain.VERDICT_CONVERGED

    def test_unknown_controller_raises_keyerror(self):
        engine, _, _, _, _ = make_engine()
        with pytest.raises(KeyError):
            engine.explain(KEY, controller="no-such-worker")

    def test_empty_engine_cannot_vouch_for_convergence(self):
        empty = explain.ExplainEngine(
            journeys=journey.JourneyTracker(registry=MetricsRegistry()),
            clock=FakeClock(),
        )
        assert empty.explain(KEY)["verdict"] == explain.VERDICT_NOT_MANAGED


# ---------------------------------------------------------------------------
# timeline assembly
# ---------------------------------------------------------------------------


class TestTimeline:
    def test_ordering_enqueue_then_recorder_then_current_wait(self):
        clock = FakeClock()
        recorder = FlightRecorder(capacity=16, clock=clock)
        engine, journeys, queue, _, _ = make_engine(
            clock=clock, flight_recorder=recorder
        )
        journeys.observe_enqueued(SVC, KEY)
        recorder.record(
            "reconcile", controller=SVC, key=KEY, result="requeued",
            reason="backoff", ring_epoch=2, duration=0.5,
        )
        recorder.record(  # another key: filtered out
            "reconcile", controller=SVC, key="default/other", result="ok",
        )
        recorder.record(  # another controller: filtered out
            "reconcile", controller=ING, key=KEY, result="ok",
        )
        recorder.record("gc-sweep", key=KEY)  # controller "": kept
        journeys.stage(SVC, KEY, journey.STAGE_REQUEUED, reason="backoff")
        queue.add_rate_limited(KEY, reason="backoff")

        timeline = engine.classify(SVC, KEY)["timeline"]
        events = [e["event"] for e in timeline]
        assert events[0] == "enqueued"
        assert events[-1] == "last-stage"
        assert events[1:-1] == ["reconcile", "gc-sweep"]
        entry = timeline[1]
        assert entry["reason"] == "backoff"
        assert entry["ring_epoch"] == 2
        assert entry["duration"] == 0.5
        # recorder entries ride oldest → newest
        assert timeline[1]["seq"] < timeline[2]["seq"]
        tail = timeline[-1]
        assert tail["stage"] == journey.STAGE_REQUEUED
        assert tail["reason"] == "backoff"

    def test_no_journey_no_timeline_noise(self):
        engine, _, _, _, _ = make_engine(
            flight_recorder=FlightRecorder(capacity=4)
        )
        assert engine.classify(SVC, KEY)["timeline"] == []


# ---------------------------------------------------------------------------
# fleet merge
# ---------------------------------------------------------------------------


def _answer(verdict, identity="r", epoch=0):
    return {
        "key": KEY, "identity": identity, "ring_epoch": epoch,
        "verdict": verdict, "controllers": {},
    }


class TestFleetMerge:
    def test_owner_answer_wins_over_not_owner(self):
        merged = explain.merge_fleet_explains({
            "peer-a": _answer(explain.VERDICT_NOT_OWNER, "a", epoch=4),
            "peer-b": _answer(explain.VERDICT_CIRCUIT_OPEN, "b", epoch=4),
        })
        assert merged["verdict"] == explain.VERDICT_CIRCUIT_OPEN
        assert merged["owner"] == "peer-b"
        assert merged["peers"]["peer-a"]["ring_epoch"] == 4
        assert merged["answer"]["identity"] == "b"

    def test_no_owner_mid_resize(self):
        merged = explain.merge_fleet_explains({
            "peer-a": _answer(explain.VERDICT_NOT_OWNER),
            "peer-b": _answer(explain.VERDICT_UNOWNED_RESIZE),
        })
        assert merged["owner"] is None
        # most blocking of the non-owner shapes: the resize window
        assert merged["verdict"] == explain.VERDICT_UNOWNED_RESIZE

    def test_multiple_owner_shaped_answers_resolve_most_blocking(self):
        # a resize race: both sides claim the key for an instant
        merged = explain.merge_fleet_explains({
            "peer-a": _answer(explain.VERDICT_CONVERGED, "a"),
            "peer-b": _answer(explain.VERDICT_BACKOFF, "b"),
        })
        assert merged["verdict"] == explain.VERDICT_BACKOFF
        assert merged["owner"] == "peer-b"

    def test_failed_peers_reported_never_dropped(self):
        merged = explain.merge_fleet_explains({
            "peer-a": _answer(explain.VERDICT_CONVERGED, "a"),
            "peer-b": {"error": "connection refused"},
        })
        assert merged["verdict"] == explain.VERDICT_CONVERGED
        assert merged["peers"]["peer-b"] == {"error": "connection refused"}


# ---------------------------------------------------------------------------
# O(1) lookup micro-assert
# ---------------------------------------------------------------------------


class ProbeRecordingQueue:
    """A queue facade that records exactly which keys the engine asks
    about — the no-fleet-enumeration pin."""

    def __init__(self):
        self.probed: list[str] = []

    def delayed_peek(self, item):
        self.probed.append(item)
        return None

    def contains(self, item):
        self.probed.append(item)
        return True


class TestO1Lookup:
    def test_classify_consults_only_the_probed_key(self):
        clock = FakeClock()
        journeys = journey.JourneyTracker(registry=MetricsRegistry(), clock=clock)
        engine = explain.ExplainEngine(journeys=journeys, clock=clock)
        queue = ProbeRecordingQueue()
        lookups: list[str] = []

        def key_to_obj(key):
            lookups.append(key)
            return object()

        engine.register_worker(SVC, queue, key_to_obj, managed=None)
        # a large in-flight population the lookup must never sweep
        for i in range(500):
            journeys.observe_enqueued(SVC, f"default/app{i}")
        answer = engine.explain("default/app7")
        assert answer["controllers"][SVC]["verdict"] == explain.VERDICT_IN_FLIGHT
        # queue consulted for the probed key only; the informer cache
        # not at all (the journey already answered)
        assert set(queue.probed) == {"default/app7"}
        assert lookups == []


# ---------------------------------------------------------------------------
# the blocked gauge
# ---------------------------------------------------------------------------


class TestBlockedGauge:
    def test_exposition_round_trip(self):
        clock = FakeClock()
        reg = MetricsRegistry()
        journeys = journey.JourneyTracker(registry=reg, clock=clock)
        queue = RateLimitingQueue(name="svc", clock=clock, metrics_registry=reg)
        engine = explain.ExplainEngine(journeys=journeys, clock=clock)
        engine.register_worker(SVC, queue, lambda key: object(), managed=None)
        engine.bind_metrics(reg)
        for key in ("default/a", "default/b"):
            journeys.observe_enqueued(SVC, key)
            queue.add_after(key, 20.0, reason="backoff")
        journeys.observe_enqueued(SVC, "default/c")
        queue.add(KEY)  # not journeyed: contributes nothing

        samples = parse_text(reg.render())
        assert samples['agac_explain_blocked{reason="backoff"}'] == 2
        assert samples['agac_explain_blocked{reason="in-flight"}'] == 1
        assert samples['agac_explain_blocked{reason="circuit-open"}'] == 0
        # every blocked verdict exports a series (zero-filled)
        for verdict in explain.BLOCKED_VERDICTS:
            assert f'agac_explain_blocked{{reason="{verdict}"}}' in samples

    def test_counts_cached_within_ttl_then_refreshed(self):
        clock = FakeClock()
        reg = MetricsRegistry()
        journeys = journey.JourneyTracker(registry=reg, clock=clock)
        queue = RateLimitingQueue(name="svc", clock=clock, metrics_registry=reg)
        engine = explain.ExplainEngine(journeys=journeys, clock=clock)
        engine.register_worker(SVC, queue, lambda key: object(), managed=None)
        journeys.observe_enqueued(SVC, KEY)
        assert engine.blocked_counts() == {explain.VERDICT_IN_FLIGHT: 1}
        journeys.observe_enqueued(SVC, "default/b")
        # within the TTL the sweep is shared, not re-run
        assert engine.blocked_counts() == {explain.VERDICT_IN_FLIGHT: 1}
        clock.advance(explain.BLOCKED_CACHE_TTL + 0.1)
        assert engine.blocked_counts() == {explain.VERDICT_IN_FLIGHT: 2}

    def test_query_counter_by_surface(self):
        reg = MetricsRegistry()
        engine = explain.ExplainEngine(
            journeys=journey.JourneyTracker(registry=reg), clock=FakeClock()
        )
        engine.bind_metrics(reg)
        engine.explain(KEY)
        engine.explain(KEY, surface="cli")
        engine.log_top_blocked()
        samples = parse_text(reg.render())
        assert samples['agac_explain_queries_total{surface="debug-endpoint"}'] == 1
        assert samples['agac_explain_queries_total{surface="cli"}'] == 1
        assert samples['agac_explain_queries_total{surface="post-mortem"}'] == 1


# ---------------------------------------------------------------------------
# catalog shape
# ---------------------------------------------------------------------------


class TestCatalog:
    def test_reason_codes_are_a_subset_of_the_catalog(self):
        assert explain.REASON_CODES <= set(explain.VERDICTS)

    def test_priority_covers_the_whole_catalog_exactly(self):
        assert sorted(explain._PRIORITY) == sorted(explain.VERDICTS)

    def test_blocked_verdicts_exclude_terminal_states(self):
        blocked = set(explain.BLOCKED_VERDICTS)
        assert explain.VERDICT_CONVERGED not in blocked
        assert explain.VERDICT_NOT_MANAGED not in blocked
        assert explain.VERDICT_DELETED not in blocked
        assert blocked <= set(explain.VERDICTS)
