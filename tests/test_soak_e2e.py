"""Soak e2e: sustained random churn, then total quiescence.

The chaos tier (`test_chaos_e2e.py`) injects cloud faults; this tier
injects *load*: a seeded stream of create/delete/annotate/port-change
operations over a fleet of Services and Ingresses while the full
controller stack runs with short resyncs.  Afterwards it asserts the
three properties churn tends to break:

1. **Convergence** — AWS state is exactly the image of the final
   cluster state: one complete chain per managed object, none for
   anything deleted or unmanaged mid-churn, records matching the
   surviving route53 annotations.
2. **Quiescence** — once converged, a settle window sees ZERO AWS
   calls: resyncs redeliver old==new updates which the controllers
   drop (the reference's resource-version guard), so steady state
   costs nothing.
3. **No residue** — every workqueue is empty; nothing is parked in
   delayed-add limbo waiting to mutate AWS after the test thinks the
   world is done.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from agac_tpu import apis
from agac_tpu.analysis import confinement, lockorder, racecheck
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cluster import FakeCluster
from agac_tpu.manager import ControllerConfig, Manager
from agac_tpu.controllers import (
    EndpointGroupBindingConfig,
    GlobalAcceleratorConfig,
    Route53Config,
)

from .fixtures import NLB_REGION, make_alb_ingress, make_lb_service
from .test_chaos_e2e import alb_hostname, chain_complete, nlb_hostname
from .test_resilience_e2e import wait_until

# Wall-clock parity check for the virtual-time port in
# tests/test_sim_e2e.py (TestSimSoakChurn): real threads and real
# sleeps keep honest what the cooperative executor models.
pytestmark = pytest.mark.slow

N_SERVICE_SLOTS = 20
N_INGRESS_SLOTS = 6
CHURN_OPS = 400
OWNER_TAG = "aws-global-accelerator-owner"


@pytest.fixture(autouse=True)
def _racecheck_watchdog():
    """Run the whole soak under the runtime lock-order/race detector:
    every workqueue mutex, informer store lock and the fake backend's
    guarded tables are instrumented (they are constructed after
    ``enable()``), and the tier fails with the offending stacks on any
    lock-order cycle or unlocked shared-dict mutation."""
    watchdog = racecheck.enable()
    try:
        yield watchdog
        watchdog.assert_clean()
        # runtime-observed acquisition order must be a subset of the
        # static lock graph (ISSUE 12): an uncovered edge is a
        # call-graph blind spot in the whole-program analysis
        violations, _ = lockorder.runtime_crosscheck(watchdog.edges())
        assert not violations, "\n".join(violations)
        # ...and every stage-tagged shared-state write must land inside
        # some active stage's statically declared footprint (ISSUE 16):
        # an observed write the table doesn't cover means the multi-core
        # dispatch plan has a call-graph blind spot
        fp_violations, _ = confinement.runtime_footprint_crosscheck(
            watchdog.stage_accesses()
        )
        assert not fp_violations, "\n".join(fp_violations)
    finally:
        racecheck.disable()


class TestSoakChurn:
    def test_churn_then_convergence_quiescence_no_residue(self):
        rng = random.Random(20260729)
        cluster = FakeCluster()
        # churn can briefly hold two accelerators for a recreated slot
        # (deletes apply asynchronously), so give the validating fake
        # headroom above the 26 slots instead of riding the default
        # 20-accelerator quota edge
        aws = FakeAWSBackend(
            quota_accelerators=N_SERVICE_SLOTS + N_INGRESS_SLOTS + 10
        )
        zone = aws.add_hosted_zone("example.com")
        for i in range(N_SERVICE_SLOTS):
            aws.add_load_balancer(f"lb{i}", NLB_REGION, nlb_hostname(i))
        for i in range(N_INGRESS_SLOTS):
            aws.add_load_balancer(
                f"k8s-default-chaos{i}-0a1b2c3d4e", NLB_REGION, alb_hostname(i)
            )

        stop = threading.Event()
        manager = Manager(resync_period=0.4)
        manager.run(
            cluster,
            ControllerConfig(
                global_accelerator=GlobalAcceleratorConfig(workers=3),
                route53=Route53Config(workers=2),
                endpoint_group_binding=EndpointGroupBindingConfig(),
            ),
            stop,
            cloud_factory=lambda region: AWSDriver(
                aws,
                aws,
                aws,
                poll_interval=0.01,
                poll_timeout=2.0,
                lb_not_active_retry=0.05,
                accelerator_missing_retry=0.05,
            ),
            block=False,
        )

        # desired state shadows what the cluster should hold;
        # key -> ("svc"|"ing", index, managed, hostnames)
        live: dict[str, tuple] = {}

        def svc_name(i):
            return f"svc{i}"

        def ing_name(i):
            return f"ing{i}"

        def churn_once():
            if rng.random() < 0.75:  # service op
                i = rng.randrange(N_SERVICE_SLOTS)
                name = svc_name(i)
                if name not in live:
                    hostnames = (
                        [f"app{i}.example.com"] if rng.random() < 0.4 else []
                    )
                    ann = (
                        {apis.ROUTE53_HOSTNAME_ANNOTATION: ",".join(hostnames)}
                        if hostnames
                        else {}
                    )
                    cluster.create(
                        "Service",
                        make_lb_service(
                            name=name, hostname=nlb_hostname(i), annotations=ann
                        ),
                    )
                    live[name] = ("svc", i, True, hostnames)
                    return
                kind, idx, managed, hostnames = live[name]
                op = rng.random()
                if op < 0.35:  # delete
                    cluster.delete("Service", "default", name)
                    del live[name]
                elif op < 0.6:  # toggle managed (and drop route53 with it)
                    obj = cluster.get("Service", "default", name)
                    if managed:
                        obj.metadata.annotations.pop(
                            apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION, None
                        )
                        obj.metadata.annotations.pop(
                            apis.ROUTE53_HOSTNAME_ANNOTATION, None
                        )
                        live[name] = (kind, idx, False, [])
                    else:
                        obj.metadata.annotations[
                            apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION
                        ] = "true"
                        live[name] = (kind, idx, True, hostnames)
                    cluster.update("Service", obj)
                elif op < 0.8 and managed:  # flip route53 annotation
                    obj = cluster.get("Service", "default", name)
                    if hostnames:
                        obj.metadata.annotations.pop(
                            apis.ROUTE53_HOSTNAME_ANNOTATION, None
                        )
                        live[name] = (kind, idx, managed, [])
                    else:
                        hs = [f"app{idx}.example.com"]
                        obj.metadata.annotations[
                            apis.ROUTE53_HOSTNAME_ANNOTATION
                        ] = ",".join(hs)
                        live[name] = (kind, idx, managed, hs)
                    cluster.update("Service", obj)
                else:  # touch (no semantic change — still an update event)
                    obj = cluster.get("Service", "default", name)
                    obj.metadata.labels["touched"] = str(rng.randrange(1 << 30))
                    cluster.update("Service", obj)
            else:  # ingress op
                i = rng.randrange(N_INGRESS_SLOTS)
                name = ing_name(i)
                if name not in live:
                    cluster.create(
                        "Ingress",
                        make_alb_ingress(name=name, hostname=alb_hostname(i)),
                    )
                    live[name] = ("ing", i, True, [])
                elif rng.random() < 0.5:
                    cluster.delete("Ingress", "default", name)
                    del live[name]
                else:
                    obj = cluster.get("Ingress", "default", name)
                    obj.metadata.labels["touched"] = str(rng.randrange(1 << 30))
                    cluster.update("Ingress", obj)

        for _ in range(CHURN_OPS):
            churn_once()
            time.sleep(0.005)

        try:
            # 1. convergence: AWS is the exact image of final state
            expected_owners = {
                (f"service/default/{n}" if kind == "svc" else f"ingress/default/{n}")
                for n, (kind, idx, managed, _) in live.items()
                if managed
            }
            expected_records = set()
            for n, (kind, idx, managed, hostnames) in live.items():
                if managed:
                    for h in hostnames:
                        expected_records.add((h + ".", "A"))
                        expected_records.add((h + ".", "TXT"))

            def converged():
                owners = set()
                for arn in aws.all_accelerator_arns():
                    tags = {t.key: t.value for t in aws.list_tags_for_resource(arn)}
                    owners.add(tags.get(OWNER_TAG))
                if owners != expected_owners:
                    return False
                names = {(r.name, r.type) for r in aws.records_in_zone(zone.id)}
                return names == expected_records

            assert wait_until(converged, timeout=30.0), (
                f"expected owners {sorted(expected_owners)}, records "
                f"{sorted(expected_records)}; got owners "
                f"{[({t.key: t.value for t in aws.list_tags_for_resource(a)}.get(OWNER_TAG)) for a in aws.all_accelerator_arns()]}, "
                f"records {sorted({(r.name, r.type) for r in aws.records_in_zone(zone.id)})}"
            )
            for n, (kind, idx, managed, _) in live.items():
                if not managed:
                    continue
                owner = f"service/default/{n}" if kind == "svc" else f"ingress/default/{n}"
                lb = nlb_hostname(idx) if kind == "svc" else alb_hostname(idx)
                assert wait_until(lambda o=owner, l=lb: chain_complete(aws, o, l)), owner

            # 2. quiescence: a settle window sees zero AWS calls even
            # though resyncs keep firing every 0.4 s
            def settled():
                before = len(aws.calls)
                time.sleep(1.2)  # three resync periods
                return len(aws.calls) == before

            assert wait_until(settled, timeout=20.0, interval=0.0), (
                "steady state still touching AWS"
            )

            # 3. no residue: every workqueue fully drained
            for name, controller in manager.controllers.items():
                for attr in ("service_queue", "ingress_queue", "workqueue"):
                    queue = getattr(controller, attr, None)
                    if queue is not None:
                        assert len(queue) == 0, f"{name}.{attr} not drained"
        finally:
            stop.set()
