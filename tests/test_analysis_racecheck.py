"""Unit tier for the runtime race/lock-order detector
(``agac_tpu/analysis/racecheck.py``): inversion and cycle detection
with offending stacks, unlocked-mutation detection through the fake
backend's guarded dicts, zero-overhead passthrough when disabled, and
an instrumented run of the real workqueue/informer machinery staying
clean.  The soak and chaos e2e tiers run with the watchdog enabled
end-to-end (``tests/test_soak_e2e.py``, ``tests/test_chaos_e2e.py``).
"""

from __future__ import annotations

import threading

import pytest

from agac_tpu.analysis import racecheck
from agac_tpu.analysis.racecheck import GuardedDict, InstrumentedLock, LockOrderWatchdog


@pytest.fixture()
def watchdog():
    wd = racecheck.enable()
    yield wd
    racecheck.disable()


def _locks(wd, *names):
    return [InstrumentedLock(n, wd) for n in names]


class TestLockOrder:
    def test_consistent_order_is_clean(self, watchdog):
        a, b = _locks(watchdog, "A", "B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert watchdog.check() == []
        assert watchdog.edges() == [("A", "B")]

    def test_inversion_across_threads_is_flagged_with_both_stacks(self, watchdog):
        a, b = _locks(watchdog, "A", "B")

        def forward():
            with a:
                with b:
                    pass

        def backward():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=forward, name="t-forward")
        t1.start(); t1.join()
        t2 = threading.Thread(target=backward, name="t-backward")
        t2.start(); t2.join()

        violations = watchdog.check()
        assert len(violations) == 1
        v = violations[0]
        assert v.kind == "lock-order-inversion"
        assert "potential deadlock" in v.message
        # both acquisition stacks are attached, naming the threads' code
        assert len(v.stacks) == 2
        assert all("backward" in s or "forward" in s for s in v.stacks)
        with pytest.raises(AssertionError, match="lock-order-inversion"):
            watchdog.assert_clean()

    def test_three_lock_cycle_is_found_by_graph_walk(self, watchdog):
        a, b, c = _locks(watchdog, "A", "B", "C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        # no 2-edge inversion exists...
        assert watchdog.violations == []
        # ...but the full walk finds A -> B -> C -> A
        violations = watchdog.check()
        assert [v.kind for v in violations] == ["lock-order-cycle"]
        assert "A -> B -> C -> A" in violations[0].message
        assert len(violations[0].stacks) == 3

    def test_reentrant_rlock_does_not_self_edge(self, watchdog):
        r = racecheck.make_rlock("R")
        with r:
            with r:
                pass
        assert watchdog.check() == []
        assert watchdog.edges() == []

    def test_condition_wait_notify_stays_clean(self, watchdog):
        # the workqueue shape: two conditions over one instrumented mutex
        mutex = racecheck.make_lock("mu")
        ready = threading.Condition(mutex)
        got = []

        def consumer():
            with mutex:
                while not got:
                    ready.wait(1.0)

        t = threading.Thread(target=consumer)
        t.start()
        with mutex:
            got.append(1)
            ready.notify()
        t.join()
        assert watchdog.check() == []


class TestGuardedDict:
    def test_mutation_under_lock_is_clean(self, watchdog):
        lock = racecheck.make_lock("d-lock")
        d = racecheck.guard_dict({}, lock, "shared")
        assert isinstance(d, GuardedDict)
        with lock:
            d["k"] = 1
            d.setdefault("j", 2)
            d.update(x=3)
            d.pop("x")
            del d["j"]
        assert watchdog.check() == [] and d == {"k": 1}

    def test_unlocked_mutation_is_flagged_with_stack(self, watchdog):
        lock = racecheck.make_lock("d-lock")
        d = racecheck.guard_dict({}, lock, "shared")
        d["k"] = 1  # no lock held
        violations = watchdog.check()
        assert [v.kind for v in violations] == ["unlocked-mutation"]
        assert "shared" in violations[0].message
        assert "test_analysis_racecheck" in violations[0].stacks[0]

    def test_lock_held_by_other_thread_does_not_count(self, watchdog):
        lock = racecheck.make_lock("d-lock")
        d = racecheck.guard_dict({}, lock, "shared")
        lock.acquire()  # agac-lint: ignore[bare-lock-acquire] -- held across the probe thread below on purpose
        try:
            t = threading.Thread(target=lambda: d.__setitem__("k", 1))
            t.start(); t.join()
        finally:
            lock.release()  # agac-lint: ignore[bare-lock-acquire] -- paired with the probe acquire above
        assert [v.kind for v in watchdog.check()] == ["unlocked-mutation"]

    def test_fake_backend_tables_are_guarded_end_to_end(self, watchdog):
        from agac_tpu.cloudprovider.aws.fake_backend import FakeAWSBackend

        backend = FakeAWSBackend()
        # the normal API path mutates under the backend lock: clean
        backend.add_load_balancer("lb", "us-west-2", "lb.elb.amazonaws.com")
        backend.add_hosted_zone("example.com")
        backend.create_accelerator("ok", "IPV4", True, [])
        assert watchdog.check() == []
        # out-of-band tampering without the lock is the seeded race
        backend._accelerators["evil"] = object()
        violations = watchdog.check()
        assert [v.kind for v in violations] == ["unlocked-mutation"]
        assert "fake-backend._accelerators" in violations[0].message


class TestDisabledPassthrough:
    def test_disabled_factories_return_plain_primitives(self):
        assert racecheck.active() is None
        lock = racecheck.make_lock("x")
        rlock = racecheck.make_rlock("x")
        assert not isinstance(lock, InstrumentedLock)
        assert type(lock) is type(threading.Lock())
        assert type(rlock) is type(threading.RLock())
        d = racecheck.guard_dict({"a": 1}, lock, "x")
        assert type(d) is dict and d == {"a": 1}

    def test_enable_returns_a_fresh_watchdog_each_time(self):
        first = racecheck.enable()
        second = racecheck.enable()
        try:
            assert first is not second
            assert racecheck.active() is second
        finally:
            racecheck.disable()


class TestInstrumentedCoreMachinery:
    def test_workqueue_under_watchdog_is_clean(self, watchdog):
        from agac_tpu.reconcile.workqueue import RateLimitingQueue

        queue = RateLimitingQueue(name="rc")
        for item in ("a", "b", "a"):
            queue.add(item)
        queue.add_after("c", 0.01)
        drained = []
        while len(drained) < 3:
            item, shutdown = queue.get(timeout=1.0)
            assert not shutdown and item is not None
            drained.append(item)
            queue.done(item)
        queue.shutdown()
        assert sorted(drained) == ["a", "b", "c"]
        watchdog.assert_clean()

    def test_informer_and_leaderelection_under_watchdog_are_clean(self, watchdog):
        from agac_tpu.cluster import FakeCluster
        from agac_tpu.cluster.informer import SharedInformerFactory
        from agac_tpu.leaderelection import LeaderElection, LeaderElectionConfig

        cluster = FakeCluster()
        factory = SharedInformerFactory(cluster, resync_period=0.05)
        factory.informer("Service")
        stop = threading.Event()
        factory.start(stop)
        assert factory.wait_for_cache_sync(stop)

        election = LeaderElection(
            "agac", "kube-system",
            LeaderElectionConfig(lease_duration=1.0, renew_deadline=0.5, retry_period=0.05),
        )
        ran = threading.Event()

        def run_fn(stop_event):
            ran.set()

        election.run(cluster, run_fn, stop)
        assert ran.is_set()
        stop.set()
        watchdog.assert_clean()
