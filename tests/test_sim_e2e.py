"""Sim-harness ports of the resilience and soak e2e tiers (ISSUE 7).

Every scenario in ``test_resilience_e2e.py`` / ``test_soak_e2e.py``
that waits on real threads and real sleeps has a virtual-time twin
here: the SAME manager stack (built by ``Manager.build``), the same
fake cluster/AWS backends, but driven by the deterministic scheduler —
so hours of virtual lease churn, settle polls and resyncs cost
milliseconds of wall clock and every run replays byte-identically.
The wall-clock originals stay behind ``-m slow`` as parity checks
that the cooperative executor didn't paper over a real-thread bug.

Also here: the scenario fuzzer's fixed-seed tier — a clean mini
corpus, seed-replay identity, and the two canary mutation runs that
prove the invariant oracles CATCH the bug classes they claim to
(a fuzzer that never fails is indistinguishable from one that cannot).

The 7-virtual-day soak at N=10k (leader churn + brownout + churn) is
the acceptance drill for the whole runtime; it rides under ``-m
slow`` because it spends real minutes, not because it sleeps.
"""

from __future__ import annotations

import random
import time

import pytest

from agac_tpu import apis
from agac_tpu.analysis import racecheck
from agac_tpu.cloudprovider.aws.driver import (
    CLUSTER_TAG_KEY,
    MANAGED_TAG_KEY,
    OWNER_TAG_KEY,
    TARGET_HOSTNAME_TAG_KEY,
)
from agac_tpu.cloudprovider.aws.health import HealthConfig
from agac_tpu.cloudprovider.aws.types import Tag
from agac_tpu.leaderelection import LeaderElectionConfig
from agac_tpu.sim import fuzz
from agac_tpu.sim.harness import SimHarness, SimHarnessConfig
from agac_tpu.sim.oracles import standard_oracles

from .fixtures import NLB_HOSTNAME, NLB_NAME, NLB_REGION, make_lb_service
from .test_chaos_e2e import alb_hostname, chain_complete, nlb_hostname

FAST_LEASE = LeaderElectionConfig(
    lease_duration=60.0, renew_deadline=15.0, retry_period=5.0
)


def world_config(**overrides) -> SimHarnessConfig:
    config = SimHarnessConfig(replicas=2, lease=FAST_LEASE, **overrides)
    return config


def converge(harness, timeout=3600.0) -> None:
    """Run to quiescence (with a settle window) and fail loudly if the
    world is still busy."""
    harness.run_for(30.0)
    assert harness.run_until_quiescent(timeout, settle_window=60.0), (
        f"world still busy: {harness.stats()}"
    )


# ---------------------------------------------------------------------------
# resilience ports (wall-clock originals: test_resilience_e2e.py)
# ---------------------------------------------------------------------------


class TestSimRestartResume:
    def test_service_created_before_any_leader_converges(self):
        """Port of test_service_created_while_down_converges_after_
        restart: the object exists before the first generation leads —
        the initial list, not the missed watch event, is the trigger."""
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.cluster.create("Service", make_lb_service())
            assert harness.aws.all_accelerator_arns() == []
            converge(harness)
            assert len(harness.aws.all_accelerator_arns()) == 1

    def test_service_created_during_leadership_gap_converges(self):
        """Harder variant virtual time makes cheap: the leader is
        hard-killed (lease NOT released), the Service appears while
        nobody leads, and the standby's takeover — one lease_duration
        later — picks it up from its fresh initial list."""
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.run_for(20.0)
            first = harness.leader()
            assert first is not None
            harness.kill_leader()
            harness.cluster.create("Service", make_lb_service())
            assert harness.leader() is None
            # the lease must expire before the standby can take over
            harness.run_for(FAST_LEASE.lease_duration + 2 * FAST_LEASE.retry_period)
            assert harness.leader() not in (None, first)
            converge(harness)
            assert len(harness.aws.all_accelerator_arns()) == 1
            assert harness.generations == 2

    def test_cleanup_resumes_across_generations(self):
        """Gen1 creates the chain; gen2 (fresh caches, fresh queues,
        fresh settle table) tears it down when the annotation goes
        away — state carries purely through cluster + AWS."""
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.cluster.create("Service", make_lb_service())
            converge(harness)
            assert len(harness.aws.all_accelerator_arns()) == 1
            harness.demote_leader()  # graceful: lease released
            harness.run_for(2 * FAST_LEASE.retry_period)
            assert harness.generations == 2

            svc = harness.cluster.get("Service", "default", "web")
            del svc.metadata.annotations[
                apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION
            ]
            harness.cluster.update("Service", svc)
            converge(harness)
            assert harness.aws.all_accelerator_arns() == []

    def test_restart_repairs_half_created_chain(self):
        """A bare owner-tagged accelerator (the torn state a crash
        leaves) is adopted and completed, never duplicated."""
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.aws.create_accelerator(
                "service-default-web",
                "IPV4",
                True,
                [
                    Tag(MANAGED_TAG_KEY, "true"),
                    Tag(OWNER_TAG_KEY, "service/default/web"),
                    Tag(TARGET_HOSTNAME_TAG_KEY, NLB_HOSTNAME),
                    Tag(CLUSTER_TAG_KEY, "default"),
                ],
            )
            arn = harness.aws.all_accelerator_arns()[0]
            assert harness.aws.list_listeners(arn, 100, None)[0] == []

            harness.cluster.create("Service", make_lb_service())
            converge(harness)
            assert harness.aws.all_accelerator_arns() == [arn]
            listeners, _ = harness.aws.list_listeners(arn, 100, None)
            assert len(listeners) == 1
            groups, _ = harness.aws.list_endpoint_groups(
                listeners[0].listener_arn, 100, None
            )
            assert len(groups) == 1

    def test_external_tamper_repaired_on_next_reconcile(self):
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.cluster.create("Service", make_lb_service())
            converge(harness)
            arn = harness.aws.all_accelerator_arns()[0]
            listeners, _ = harness.aws.list_listeners(arn, 100, None)
            groups, _ = harness.aws.list_endpoint_groups(
                listeners[0].listener_arn, 100, None
            )
            harness.aws.delete_endpoint_group(groups[0].endpoint_group_arn)

            svc = harness.cluster.get("Service", "default", "web")
            svc.metadata.labels["touched"] = "true"
            harness.cluster.update("Service", svc)
            converge(harness)
            assert (
                len(
                    harness.aws.list_endpoint_groups(
                        listeners[0].listener_arn, 100, None
                    )[0]
                )
                == 1
            )


class TestSimFaultInjection:
    def test_create_listener_throttled_then_converges(self):
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.fault_plan.throttle("create_listener", times=2)
            harness.cluster.create("Service", make_lb_service())
            converge(harness)
            arns = harness.aws.all_accelerator_arns()
            assert len(arns) == 1
            assert len(harness.aws.list_listeners(arns[0], 100, None)[0]) == 1
            assert harness.fault_plan.faults_served == 2

    def test_describe_lb_outage_retries_until_healthy(self):
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.fault_plan.throttle("describe_load_balancers", times=3)
            harness.cluster.create("Service", make_lb_service())
            converge(harness)
            assert len(harness.aws.all_accelerator_arns()) == 1
            assert harness.fault_plan.faults_served == 3

    def test_crash_mid_create_recovered_by_standby(self):
        """A SimulatedCrash at the CreateListener boundary kills the
        leading generation mid-chain (lease still held); the standby
        takes over after lease expiry and repairs the half-built
        chain — the in-sim twin of the rc-137 process drills."""
        with SimHarness(config=world_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.fault_plan.crash("create_listener", when="before")
            harness.run_for(20.0)
            harness.cluster.create("Service", make_lb_service())
            converge(harness, timeout=7200.0)
            assert harness.generations >= 2
            arns = harness.aws.all_accelerator_arns()
            assert len(arns) == 1
            assert len(harness.aws.list_listeners(arns[0], 100, None)[0]) == 1
            assert standard_oracles(harness) == []

    def test_leader_failover_mid_fleet_converges(self):
        """Kill the leader with half the fleet converged and more work
        arriving; the next generation finishes without orphaning or
        duplicating anything (port of the two-process failover
        drill)."""
        slots = 10
        with SimHarness(
            config=world_config(quota_accelerators=slots + 5)
        ) as harness:
            for i in range(slots):
                harness.aws.add_load_balancer(
                    f"lb{i}", NLB_REGION, nlb_hostname(i)
                )
            harness.aws.add_hosted_zone("example.com")
            for i in range(slots // 2):
                harness.cluster.create(
                    "Service", fuzz._make_service(f"svc{i}", i, i % 3 == 0)
                )
            harness.run_for(90.0)  # mid-flight, not necessarily settled
            harness.kill_leader()
            for i in range(slots // 2, slots):
                harness.cluster.create(
                    "Service", fuzz._make_service(f"svc{i}", i, i % 3 == 0)
                )
            converge(harness, timeout=7200.0)
            assert harness.generations == 2
            assert standard_oracles(harness) == []


# ---------------------------------------------------------------------------
# soak port (wall-clock original: test_soak_e2e.py)
# ---------------------------------------------------------------------------


class TestSimSoakChurn:
    def test_churn_then_convergence_quiescence_no_residue(self):
        """The soak tier's three properties — convergence, zero-call
        quiescence, no queue residue — under seeded Service+Ingress
        churn, with the racecheck watchdog armed, in virtual time."""
        n_service, n_ingress = 20, 6
        rng = random.Random(20260729)
        watchdog = racecheck.enable()
        try:
            with SimHarness(
                config=world_config(
                    resync_period=300.0,
                    quota_accelerators=n_service + n_ingress + 10,
                )
            ) as harness:
                zone = harness.aws.add_hosted_zone("example.com")
                for i in range(n_service):
                    harness.aws.add_load_balancer(
                        f"lb{i}", NLB_REGION, nlb_hostname(i)
                    )
                for i in range(n_ingress):
                    harness.aws.add_load_balancer(
                        f"k8s-default-chaos{i}-0a1b2c3d4e",
                        NLB_REGION,
                        alb_hostname(i),
                    )
                harness.run_for(20.0)

                from .fixtures import make_alb_ingress

                live: dict[str, tuple] = {}

                def churn_once():
                    if rng.random() < 0.75:
                        i = rng.randrange(n_service)
                        name = f"svc{i}"
                        if name not in live:
                            harness.cluster.create(
                                "Service",
                                fuzz._make_service(name, i, rng.random() < 0.4),
                            )
                            live[name] = ("svc", i)
                        elif rng.random() < 0.45:
                            harness.cluster.delete("Service", "default", name)
                            del live[name]
                        else:
                            obj = harness.cluster.get("Service", "default", name)
                            obj.metadata.labels["touched"] = str(
                                rng.randrange(1 << 30)
                            )
                            harness.cluster.update("Service", obj)
                    else:
                        i = rng.randrange(n_ingress)
                        name = f"ing{i}"
                        if name not in live:
                            harness.cluster.create(
                                "Ingress",
                                make_alb_ingress(name=name, hostname=alb_hostname(i)),
                            )
                            live[name] = ("ing", i)
                        elif rng.random() < 0.5:
                            harness.cluster.delete("Ingress", "default", name)
                            del live[name]
                        else:
                            obj = harness.cluster.get("Ingress", "default", name)
                            obj.metadata.labels["touched"] = str(
                                rng.randrange(1 << 30)
                            )
                            harness.cluster.update("Ingress", obj)

                for _ in range(150):
                    churn_once()
                    harness.run_for(rng.uniform(1.0, 20.0))

                # convergence + pending-settle drained + no residue
                assert harness.run_until_quiescent(7200.0, settle_window=0.0)
                assert standard_oracles(harness) == []

                # zero-call quiescence across multiple resync periods
                calls_before = len(harness.aws.calls)
                harness.run_for(3 * 300.0)
                assert len(harness.aws.calls) == calls_before, (
                    "steady state still touching AWS"
                )

                # per-owner chain integrity, exactly like the original
                for name, (kind, i) in live.items():
                    owner = (
                        f"service/default/{name}"
                        if kind == "svc"
                        else f"ingress/default/{name}"
                    )
                    lb = nlb_hostname(i) if kind == "svc" else alb_hostname(i)
                    assert chain_complete(harness.aws, owner, lb), owner
            watchdog.assert_clean()
        finally:
            racecheck.disable()


# ---------------------------------------------------------------------------
# the fuzzer: fixed-seed corpus, replay identity, canary mutations
# ---------------------------------------------------------------------------

MINI_SEED = 3

# hypothesis is optional here on purpose: CI installs it (test.yml),
# but its absence must only skip the seed-sweep property below — never
# the rest of this module (a module-level importorskip would silently
# drop every sim port with it)
try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover
    HAVE_HYPOTHESIS = False


if HAVE_HYPOTHESIS:

    class TestHypothesisSeedSweep:
        @settings(max_examples=5, deadline=None, derandomize=True)
        @given(seed=st.integers(min_value=0, max_value=2**31 - 1))
        def test_seed_sweep_passes_oracles(self, seed):
            """Hypothesis drives seed discovery; each drawn seed is a
            fully deterministic scenario, so a failure here shrinks to
            a minimal seed that replays byte-identically via the CLI."""
            result = fuzz.run_scenario(seed, profile="mini")
            assert result.ok, (
                f"seed {seed} violated: {result.violations} — replay with "
                f"`python -m agac_tpu.sim.fuzz --seeds {seed} --profile mini`"
            )


class TestScenarioFuzzer:

    def test_mini_seed_passes_all_oracles(self):
        result = fuzz.run_scenario(MINI_SEED, profile="mini")
        assert result.ok, result.violations
        assert result.stats["virtual_time"] > 900.0

    def test_same_seed_replays_byte_identically(self):
        first = fuzz.run_scenario(MINI_SEED, profile="mini")
        second = fuzz.run_scenario(MINI_SEED, profile="mini")
        assert first.trace_hash == second.trace_hash
        assert first.stats["aws_calls"] == second.stats["aws_calls"]
        assert first.violations == second.violations

    def test_canary_drop_txt_delete_is_caught(self):
        """Mutation run: cleanup that 'forgets' owner-TXT deletes must
        trip the record-atomicity/convergence oracles, with a
        replayable seed."""
        result = fuzz.run_scenario(
            MINI_SEED, profile="mini", canary="drop-txt-delete"
        )
        assert not result.ok
        assert any(
            "atomicity" in v or "convergence" in v for v in result.violations
        ), result.violations
        replay = fuzz.run_scenario(
            MINI_SEED, profile="mini", canary="drop-txt-delete"
        )
        assert replay.trace_hash == result.trace_hash
        assert replay.violations == result.violations

    def test_no_faults_run_passes_the_slo_oracle(self):
        """The SLO oracle's clean half (ISSUE 9): with every fault
        composition dropped, the churn-only scenario must meet every
        convergence objective — a fault-free run that misses p99 is a
        real regression, and the oracle is ARMED (its violations fail
        the scenario)."""
        result = fuzz.run_scenario(MINI_SEED, profile="mini", no_faults=True)
        assert result.ok, result.violations
        slo_stats = result.stats["slo"]
        assert slo_stats["violations"] == []
        # journeys were actually measured, not vacuously absent
        assert slo_stats["journeys"]["converged_total"] > 0
        assert slo_stats["journeys"]["inflight"] == 0

    def test_canary_slo_brownout_is_caught_and_sheds(self):
        """Mutation run (ISSUE 9): a sustained GA brownout must trip
        the convergence-SLO oracle AND be observed driving burn-gated
        shedding of deferrable load — an SLO plane that cannot fail,
        or a shed gate that never fires, proves nothing."""
        result = fuzz.run_scenario(
            MINI_SEED, profile="mini", canary="slo-brownout", no_faults=True
        )
        assert not result.ok
        assert any(v.startswith("slo:") for v in result.violations), (
            result.violations
        )
        assert result.stats["slo"]["shed_activations"] >= 1, (
            "burn-gated shedding was never observed"
        )

    def test_canary_explain_lie_is_caught(self):
        """Mutation run (ISSUE 15): a lying classifier that reports
        every key "converged" during the same GA brownout must be
        caught by the explain ground-truth oracle — unconverged keys
        claiming a terminal verdict at probe time.  An explain plane
        whose oracle cannot detect a lie proves nothing."""
        result = fuzz.run_scenario(
            MINI_SEED, profile="mini", canary="explain-lie", no_faults=True
        )
        assert not result.ok
        assert any(v.startswith("explain:") for v in result.violations), (
            result.violations
        )

    def test_truthful_classifier_is_clean_under_brownout(self):
        """The explain oracle's clean half: the same brownout with the
        real classifier must produce ZERO explain violations — probes
        fire mid-outage and every blocked key classifies inside the
        brownout verdict set, never `unknown`, never `converged`."""
        result = fuzz.run_scenario(
            MINI_SEED, profile="mini", canary="slo-brownout", no_faults=True
        )
        explain_violations = [
            v for v in result.violations if v.startswith("explain:")
        ]
        assert explain_violations == []

    def test_canary_gc_stale_owner_cache_is_caught(self):
        """Mutation run: a GC sweeper trusting a stale owner cache
        (grace disabled) reaps live owners — the live-owner deletion
        oracle must catch it."""
        result = fuzz.run_scenario(
            MINI_SEED, profile="mini", canary="gc-stale-owner-cache"
        )
        assert not result.ok
        assert any("LIVE owner" in v or "convergence" in v for v in result.violations), (
            result.violations
        )

    def test_cli_reports_failure_and_writes_artifact(self, tmp_path):
        rc = fuzz.main(
            [
                "--seeds", str(MINI_SEED),
                "--profile", "mini",
                "--canary", "drop-txt-delete",
                "--artifacts", str(tmp_path),
            ]
        )
        assert rc == 1
        artifact = tmp_path / f"seed-{MINI_SEED}.json"
        assert artifact.exists()
        payload = artifact.read_text()
        assert "trace_hash" in payload and "replay" in payload


# ---------------------------------------------------------------------------
# the acceptance drill: 7 virtual days, N=10k, composed degradation
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSevenDaySoak:
    def test_seven_virtual_days_at_10k_under_ten_minutes(self):
        n = 10_000
        start_wall = time.monotonic()
        config = SimHarnessConfig(
            replicas=2,
            resync_period=6 * 3600.0,
            drift_tick_period=6 * 3600.0,
            gc_sweep_period=12 * 3600.0,
            settle_poll_interval=30.0,
            # production-shaped snapshot TTL: local writes are
            # write-through, so a 30 s TTL at N=10k only buys extra
            # full reloads (drift detection belongs to drift ticks)
            discovery_ttl=300.0,
            quota_accelerators=n + 50,
            health=HealthConfig(
                window=60.0,
                min_calls=6,
                failure_ratio=0.5,
                open_duration=30.0,
                probe_budget=1,
                aimd_qps=200.0,
            ),
            lease=LeaderElectionConfig(
                lease_duration=120.0, renew_deadline=60.0, retry_period=30.0
            ),
        )
        rng = random.Random(7)
        with SimHarness(config=config) as harness:
            for i in range(n):
                harness.aws.add_load_balancer(
                    f"lb{i}", NLB_REGION, nlb_hostname(i)
                )
            harness.aws.add_hosted_zone("example.com")

            def creator():
                # the whole fleet arrives across the first two virtual
                # hours — a rollout, not a thundering herd
                for i in range(n):
                    harness.cluster.create(
                        "Service",
                        fuzz._make_service(f"svc{i}", i, i % 20 == 0),
                    )
                    yield 7200.0 / n

            def churner():
                # steady churn for the rest of the week
                for _ in range(600):
                    slot = rng.randrange(n)
                    name = f"svc{slot}"
                    try:
                        obj = harness.cluster.get("Service", "default", name)
                    except Exception:
                        yield 600.0
                        continue
                    if rng.random() < 0.3:
                        harness.cluster.delete("Service", "default", name)

                        def recreate(slot=slot, name=name):
                            harness.cluster.create(
                                "Service",
                                fuzz._make_service(name, slot, slot % 20 == 0),
                            )

                        harness.after(
                            rng.uniform(600.0, 3600.0), recreate, f"recreate:{name}"
                        )
                    else:
                        obj.metadata.labels["touched"] = str(rng.randrange(1 << 30))
                        harness.cluster.update("Service", obj)
                    yield rng.uniform(300.0, 1200.0)

            harness.spawn(creator(), "creator")
            harness.after(8 * 3600.0, lambda: harness.spawn(churner(), "churn"), "arm-churn")
            # leader churn: a hard kill on day 2, a graceful demotion
            # on day 4
            harness.after(2 * 86400.0, harness.kill_leader, "kill-leader")
            harness.after(4 * 86400.0, harness.demote_leader, "demote-leader")
            # a 2-hour Route53 brownout on day 3
            harness.after(
                3 * 86400.0,
                lambda: harness.fault_plan.outage(
                    "change_resource_record_sets",
                    "list_resource_record_sets",
                    "list_hosted_zones",
                ),
                "brownout-start",
            )
            harness.after(
                3 * 86400.0 + 2 * 3600.0,
                lambda: harness.fault_plan.restore(),
                "brownout-end",
            )

            harness.run_for(7 * 86400.0)
            assert harness.run_until_quiescent(12 * 3600.0, settle_window=600.0), (
                harness.stats()
            )
            violations = standard_oracles(harness)
            assert violations == [], violations[:10]
            assert harness.generations >= 3
            stats = harness.stats()
            assert stats["virtual_time"] >= 7 * 86400.0

        wall = time.monotonic() - start_wall
        assert wall < 600.0, f"7-day soak took {wall:.0f}s wall (budget 600s)"
