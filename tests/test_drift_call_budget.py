"""Drift-tick call-budget regression tier (ISSUE 2): a scaled-down
converged fleet, one explicitly driven ticker round through
``Manager.drift_tick()`` (the same source wiring the in-process ticker
and the bench use), and a hard ceiling on the AWS calls that round may
cost with the coalesced read plane on.

The ceiling is the contract the read plane exists to keep: one GA read
per accelerator (the chain-tail verify), one ListResourceRecordSets
per hosted zone, batched DescribeLoadBalancers, one
DescribeEndpointGroup per binding — and ZERO mutates on a converged
fleet.  A stray per-item read sneaking back into a verify path fails
this tier long before it shows up as a 4x quota bill in the full
bench (where the same regression is only visible as a trajectory
change in BENCH_r*.json)."""

from __future__ import annotations

import threading
import time

import pytest

from agac_tpu import apis
from agac_tpu.apis.endpointgroupbinding.v1alpha1 import (
    EndpointGroupBinding,
    EndpointGroupBindingSpec,
    ServiceReference,
)
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cloudprovider.aws.cache import (
    AcceleratorTopologyCache,
    DiscoveryCache,
    HostedZoneCache,
    LoadBalancerCoalescer,
    RecordSetCache,
)
from agac_tpu.controllers import (
    EndpointGroupBindingConfig,
    GlobalAcceleratorConfig,
    Route53Config,
)
from agac_tpu.cluster import FakeCluster, ObjectMeta
from agac_tpu.manager import ControllerConfig, Manager

from .fixtures import NLB_REGION, make_lb_service

N_SERVICES = 6
N_ZONES = 2
# tick scope of the verification caches: long enough that one tick's
# reads coalesce, short enough to be expired by the measured round
# after the quiescence wait below
TICK_TTL = 0.3
QUIET_NEED = 0.5

READ_OPS = (
    "ListAccelerators", "ListTagsForResource", "ListListeners",
    "ListEndpointGroups", "DescribeAccelerator", "DescribeEndpointGroup",
    "DescribeLoadBalancers", "ListHostedZones", "ListHostedZonesByName",
    "ListResourceRecordSets",
)
MUTATE_OPS = (
    "CreateAccelerator", "UpdateAccelerator", "DeleteAccelerator",
    "CreateListener", "UpdateListener", "DeleteListener",
    "CreateEndpointGroup", "UpdateEndpointGroup", "DeleteEndpointGroup",
    "AddEndpoints", "RemoveEndpoints", "TagResource",
    "ChangeResourceRecordSets",
)

# The budget, itemized (see module docstring).  LB describes are
# batched but batch sizes depend on worker interleaving, so the
# ceiling admits the degenerate all-singles case:
#   6 ListEndpointGroups (chain verify, one per accelerator)
# + 2 ListResourceRecordSets (one per zone)
# + 7 DescribeLoadBalancers wire calls max (6 services + 1 binding ref)
# + 1 DescribeEndpointGroup (binding verify)
# + 4 slack (an unlucky discovery/zone refresh landing mid-tick)
TICK_CALL_CEILING = 20


def hostname_of(i: int) -> str:
    return f"svc{i}.z{i % N_ZONES}.budget.example.com"


def lb_hostname(i: int) -> str:
    return f"lb{i}-0123456789abcdef.elb.us-west-2.amazonaws.com"


def wait_until(probe, timeout=30.0, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if probe():
            return
        time.sleep(0.02)
    pytest.fail(f"timed out waiting for {message}")


def wait_quiescent(aws, timeout=30.0):
    """Block until no AWS call lands for QUIET_NEED seconds (also lets
    the tick-scoped TTLs expire, so the measured round re-reads)."""
    deadline = time.monotonic() + timeout
    last = len(aws.calls)
    quiet_since = time.monotonic()
    while time.monotonic() < deadline:
        time.sleep(0.05)
        cur = len(aws.calls)
        if cur != last:
            last, quiet_since = cur, time.monotonic()
        elif time.monotonic() - quiet_since >= QUIET_NEED:
            return
    pytest.fail("fleet never went AWS-quiescent")


def test_converged_tick_stays_within_call_budget():
    aws = FakeAWSBackend(quota_accelerators=N_SERVICES + 5)
    cluster = FakeCluster()
    zones = [aws.add_hosted_zone(f"z{k}.budget.example.com") for k in range(N_ZONES)]
    for i in range(N_SERVICES):
        aws.add_load_balancer(f"lb{i}", NLB_REGION, lb_hostname(i))

    # one binding bound into an out-of-band endpoint group (the same
    # fixture shape the bench and EGB drift tests use)
    seed_driver = AWSDriver(aws, aws, aws)
    seed_svc = make_lb_service(name="seed", hostname=lb_hostname(0))
    arn, _, _ = seed_driver.ensure_global_accelerator_for_service(
        seed_svc, seed_svc.status.load_balancer.ingress[0],
        "external", "lb0", NLB_REGION,
    )
    seed_eg = seed_driver.get_endpoint_group(
        seed_driver.get_listener(arn).listener_arn
    )

    for i in range(N_SERVICES):
        svc = make_lb_service(name=f"svc{i}", hostname=lb_hostname(i))
        svc.metadata.annotations[apis.ROUTE53_HOSTNAME_ANNOTATION] = hostname_of(i)
        # the fixture names its LB after the service; point it at ours
        cluster.create("Service", svc)
    cluster.create(
        "EndpointGroupBinding",
        EndpointGroupBinding(
            metadata=ObjectMeta(name="binding", namespace="default"),
            spec=EndpointGroupBindingSpec(
                endpoint_group_arn=seed_eg.endpoint_group_arn,
                weight=100,
                service_ref=ServiceReference(name="svc0"),
            ),
        ),
    )

    # shared read plane, exactly as the factory wires it (discovery /
    # zone snapshots sized to stay warm across the measured tick)
    discovery = DiscoveryCache(ttl=300.0)
    zone_cache = HostedZoneCache(ttl=300.0)
    topology = AcceleratorTopologyCache(verify_ttl=TICK_TTL, full_ttl=300.0)
    records = RecordSetCache(ttl=TICK_TTL)
    lbs = LoadBalancerCoalescer(ttl=TICK_TTL, batch_window=0.02)

    stop = threading.Event()
    dormant = 10_000.0  # > 0 arms the EGB converged-path verify; never fires
    config = ControllerConfig(
        global_accelerator=GlobalAcceleratorConfig(
            workers=2, queue_qps=1000.0, queue_burst=1000,
            drift_resync_period=dormant,
        ),
        route53=Route53Config(
            workers=2, queue_qps=1000.0, queue_burst=1000,
            drift_resync_period=dormant,
        ),
        endpoint_group_binding=EndpointGroupBindingConfig(
            workers=1, queue_qps=1000.0, queue_burst=1000,
            drift_resync_period=dormant,
        ),
    )
    manager = Manager(resync_period=dormant)
    manager.run(
        cluster, config, stop,
        cloud_factory=lambda region: AWSDriver(
            aws, aws, aws,
            accelerator_missing_retry=0.1,
            discovery_cache=discovery,
            zone_cache=zone_cache,
            topology_cache=topology,
            record_cache=records,
            lb_coalescer=lbs,
        ),
        block=False,
    )
    try:
        def converged():
            if len(aws.all_accelerator_arns()) < 1 + N_SERVICES:
                return False
            records_up = sum(len(aws.records_in_zone(z.id)) for z in zones)
            if records_up < 2 * N_SERVICES:
                return False
            binding = cluster.get("EndpointGroupBinding", "default", "binding")
            return len(binding.status.endpoint_ids) == 1

        wait_until(converged, message="fleet convergence")
        wait_quiescent(aws)

        before = len(aws.calls)
        enqueued = manager.drift_tick()
        assert enqueued >= 2 * N_SERVICES + 1  # GA + Route53 sources + EGB
        wait_quiescent(aws)
        tick_calls = aws.calls[before:]
    finally:
        stop.set()

    by_op: dict[str, int] = {}
    for call in tick_calls:
        by_op[call[0]] = by_op.get(call[0], 0) + 1

    mutates = {op: n for op, n in by_op.items() if op in MUTATE_OPS}
    assert not mutates, f"converged tick mutated AWS: {mutates}"
    total = sum(n for op, n in by_op.items() if op in READ_OPS)
    assert total <= TICK_CALL_CEILING, (
        f"drift tick cost {total} AWS calls (ceiling {TICK_CALL_CEILING}): {by_op}"
    )
    # the per-object tag-read hot spot stays dead (ISSUE 6 satellite):
    # a converged tick reads tags from the discovery snapshot, never
    # one ListTagsForResource per object — the cap admits only an
    # unlucky snapshot refresh landing mid-tick (incremental, so it
    # re-reads new arns only; a full O(N) re-list here would blow this)
    assert by_op.get("ListTagsForResource", 0) <= 2, by_op
    # and the tick genuinely VERIFIED, not just skipped reads: every
    # accelerator chain tail re-read, every zone re-listed, the
    # binding's endpoint group re-described
    assert by_op.get("ListEndpointGroups", 0) >= N_SERVICES, by_op
    assert by_op.get("ListResourceRecordSets", 0) >= N_ZONES, by_op
    assert by_op.get("DescribeEndpointGroup", 0) >= 1, by_op
    # LB verification still covered every distinct LB on the wire
    # (the binding's ref shares svc0's lb0 entry within the tick —
    # that cross-controller hit is the coalescing working)
    lb_lookups = sum(size for op, size in aws.calls[before:] if op == "DescribeLoadBalancers")
    assert lb_lookups >= N_SERVICES, "tick skipped LB verification"
