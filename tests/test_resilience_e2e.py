"""Resilience e2e: restart-resume and fault injection.

The reference has no local persistence — desired state lives in the
kube objects, actual state is re-read from AWS, ownership is recorded
in the external system itself (GA tags), so a controller restart
resumes by cache resync (SURVEY.md §5 "checkpoint/resume").  These
tests prove the rebuild preserves that property: a fresh manager over
the same cluster+AWS state picks up exactly where the old one left
off, including repairing a chain a crash left half-created, and AWS
API faults only delay convergence (rate-limited retry), never corrupt
it.
"""

import threading
import time

import pytest

from agac_tpu import apis
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cloudprovider.aws.driver import (
    CLUSTER_TAG_KEY,
    MANAGED_TAG_KEY,
    OWNER_TAG_KEY,
    TARGET_HOSTNAME_TAG_KEY,
)
from agac_tpu.cloudprovider.aws.types import Tag
from agac_tpu.cluster import FakeCluster
from agac_tpu.manager import ControllerConfig, Manager

from .fixtures import NLB_HOSTNAME, NLB_NAME, NLB_REGION, make_lb_service

# Wall-clock parity check for the virtual-time ports in
# tests/test_sim_e2e.py (TestSimRestartResume / TestSimFaultInjection):
# real threads and real sleeps keep honest what the cooperative
# executor models.
pytestmark = pytest.mark.slow

POLL_TIMEOUT = 10.0


def wait_until(pred, timeout=POLL_TIMEOUT, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def start_manager(cluster, aws, config=None, **driver_kwargs):
    """One controller 'process': returns its stop event."""
    stop = threading.Event()
    kwargs = dict(
        poll_interval=0.01,
        poll_timeout=2.0,
        lb_not_active_retry=0.05,
        accelerator_missing_retry=0.05,
    )
    kwargs.update(driver_kwargs)
    Manager(resync_period=0.3).run(
        cluster,
        config or ControllerConfig(),
        stop,
        cloud_factory=lambda region: AWSDriver(aws, aws, aws, **kwargs),
        block=False,
    )
    return stop


@pytest.fixture
def world():
    cluster = FakeCluster()
    aws = FakeAWSBackend()
    aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
    return cluster, aws


class TestRestartResume:
    def test_service_created_while_down_converges_after_restart(self, world):
        """A Service created during a controller outage is picked up
        by the next generation's initial list — the trigger is level
        (current state), not the missed watch event."""
        cluster, aws = world
        cluster.create("Service", make_lb_service())
        assert aws.all_accelerator_arns() == []  # nobody running yet

        stop = start_manager(cluster, aws)
        try:
            assert wait_until(lambda: len(aws.all_accelerator_arns()) == 1)
        finally:
            stop.set()

    def test_cleanup_resumes_across_generations(self, world):
        """Convergence state carries across restarts purely through
        cluster + AWS state: gen1 creates the chain, gen2 (fresh
        caches, fresh queues) tears it down when the annotation goes
        away — no handoff, no local persistence."""
        cluster, aws = world
        gen1 = start_manager(cluster, aws)
        cluster.create("Service", make_lb_service())
        assert wait_until(lambda: len(aws.all_accelerator_arns()) == 1)
        gen1.set()  # process gone
        time.sleep(0.1)

        gen2 = start_manager(cluster, aws)
        try:
            # the annotation is removed while gen2 is leading; its
            # update handler fires exactly like gen1's would have
            svc = cluster.get("Service", "default", "web")
            del svc.metadata.annotations[
                apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION
            ]
            cluster.update("Service", svc)
            assert wait_until(lambda: aws.all_accelerator_arns() == [])
        finally:
            gen2.set()

    def test_restart_repairs_half_created_chain(self, world):
        """A crash after CreateAccelerator but before CreateListener
        leaves a bare accelerator with ownership tags.  The next
        generation's update path create-if-missing repairs the chain
        (reference ``global_accelerator.go:288-347``)."""
        cluster, aws = world
        # simulate the torn state the crash left behind: accelerator
        # with the exact ownership tags, no listener/endpoint group
        aws.create_accelerator(
            "service-default-web",
            "IPV4",
            True,
            [
                Tag(MANAGED_TAG_KEY, "true"),
                Tag(OWNER_TAG_KEY, "service/default/web"),
                Tag(TARGET_HOSTNAME_TAG_KEY, NLB_HOSTNAME),
                Tag(CLUSTER_TAG_KEY, "default"),
            ],
        )
        arn = aws.all_accelerator_arns()[0]
        assert aws.list_listeners(arn, 100, None)[0] == []

        cluster.create("Service", make_lb_service())
        stop = start_manager(cluster, aws)
        try:
            # no duplicate accelerator; listener + endpoint group added
            def chain_complete():
                arns = aws.all_accelerator_arns()
                if arns != [arn]:
                    return False
                listeners, _ = aws.list_listeners(arn, 100, None)
                if len(listeners) != 1:
                    return False
                groups, _ = aws.list_endpoint_groups(listeners[0].listener_arn, 100, None)
                return len(groups) == 1

            assert wait_until(chain_complete)
        finally:
            stop.set()

    def test_external_tamper_repaired_on_next_reconcile(self, world):
        """An out-of-band endpoint-group deletion is repaired the next
        time the object is reconciled (any real update re-triggers;
        resync events with old==new are deliberately dropped, matching
        the reference's DeepEqual guard, ``controller.go:100-102``)."""
        cluster, aws = world
        stop = start_manager(cluster, aws)
        try:
            cluster.create("Service", make_lb_service())
            assert wait_until(lambda: len(aws.all_accelerator_arns()) == 1)
            arn = aws.all_accelerator_arns()[0]
            listeners, _ = aws.list_listeners(arn, 100, None)
            groups, _ = aws.list_endpoint_groups(listeners[0].listener_arn, 100, None)
            aws.delete_endpoint_group(groups[0].endpoint_group_arn)
            assert aws.list_endpoint_groups(listeners[0].listener_arn, 100, None)[0] == []

            # any genuine object change re-triggers reconcile
            svc = cluster.get("Service", "default", "web")
            svc.metadata.labels["touched"] = "true"
            cluster.update("Service", svc)
            assert wait_until(
                lambda: len(
                    aws.list_endpoint_groups(listeners[0].listener_arn, 100, None)[0]
                )
                == 1
            )
        finally:
            stop.set()


class TestCleanShutdown:
    def test_no_thread_leak_across_generations(self, world):
        """Every manager generation's threads (workers, informer
        watch/dispatch, queue delay-wakers) exit when stop fires —
        leader-election failover restarts the manager in-process, so
        leaked threads would accumulate until OOM."""
        import threading as threading_mod

        from agac_tpu.controllers import (
            EndpointGroupBindingConfig,
            GlobalAcceleratorConfig,
            Route53Config,
        )

        baseline = threading_mod.active_count()
        # drift resync ON so the ticker threads (one per controller)
        # are part of what each generation must tear down
        drift_config = ControllerConfig(
            global_accelerator=GlobalAcceleratorConfig(drift_resync_period=0.1),
            route53=Route53Config(drift_resync_period=0.1),
            endpoint_group_binding=EndpointGroupBindingConfig(
                drift_resync_period=0.1
            ),
        )
        for _ in range(3):
            cluster, aws = FakeCluster(), FakeAWSBackend()
            aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            stop = start_manager(cluster, aws, config=drift_config)
            cluster.create("Service", make_lb_service())
            assert wait_until(lambda: len(aws.all_accelerator_arns()) == 1)
            stop.set()
            assert wait_until(
                lambda: threading_mod.active_count() <= baseline, timeout=5.0
            ), [t.name for t in threading_mod.enumerate()]


def throttling_backend(op_name: str, failures: int) -> FakeAWSBackend:
    """Fails the first N calls of one operation with a retryable API
    error — the ThrottlingException shape, scripted through the
    first-class FaultPlan (``throttle-N-times``)."""
    aws = FakeAWSBackend()
    aws.install_fault_plan().throttle(op_name, times=failures)
    return aws


class TestFaultInjection:
    def test_create_listener_throttled_then_converges(self, world):
        """Mid-chain failure triggers rollback (no orphaned
        accelerator) and rate-limited retry eventually converges."""
        cluster, _ = world
        aws = throttling_backend("create_listener", failures=2)
        aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
        stop = start_manager(cluster, aws)
        try:
            cluster.create("Service", make_lb_service())
            assert wait_until(lambda: len(aws.all_accelerator_arns()) == 1)
            arn = aws.all_accelerator_arns()[0]
            assert wait_until(lambda: len(aws.list_listeners(arn, 100, None)[0]) == 1)
            assert aws.fault_plan.faults_served == 2
        finally:
            stop.set()

    def test_describe_lb_outage_retries_until_healthy(self, world):
        cluster, _ = world
        aws = throttling_backend("describe_load_balancers", failures=3)
        aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
        stop = start_manager(cluster, aws)
        try:
            cluster.create("Service", make_lb_service())
            assert wait_until(lambda: len(aws.all_accelerator_arns()) == 1)
            assert aws.fault_plan.faults_served == 3
        finally:
            stop.set()
