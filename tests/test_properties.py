"""Property-based tier (hypothesis): invariants the table-driven
tests can't sweep exhaustively.

The reference's contract surfaces with *unbounded input spaces* —
wire serialization of arbitrary objects, hostname reverse-engineering
of arbitrary strings, SigV4 canonicalization of arbitrary header
sets, queue semantics under arbitrary op sequences — get randomized
sweeps here on every ``make test``.  Each property is an invariant
the rest of the framework silently relies on:

- serde round-trips losslessly and ignores unknown keys (the CRD
  wire-compatibility contract, SURVEY.md §2 row 16/17);
- the LB hostname parser recovers (name, region) from every valid
  hostname shape and raises ONLY ValueError on garbage (reference
  ``load_balancer.go:32-98`` — a stray exception type would escape
  the controllers' ValueError handling);
- SigV4 signatures are invariant to header order and name casing
  (AWS canonicalization, pinned by vectors in
  ``test_sigv4_aws_vectors.py`` — this sweeps the space between them);
- the workqueue's dedup/processing-exclusion semantics (client-go's
  Type contract) hold under arbitrary add/get/done interleavings;
- the accelerator-name clamp is total, deterministic, and bounded.
"""

from __future__ import annotations

import datetime
from types import SimpleNamespace

import pytest

# CI installs hypothesis (test.yml, the ADVICE r5 #1 fix); environments
# without it skip this tier at collection instead of erroring
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, assume, given, settings, strategies as st

from agac_tpu.apis.endpointgroupbinding import (
    EndpointGroupBinding,
    EndpointGroupBindingSpec,
    EndpointGroupBindingStatus,
    IngressReference,
    ServiceReference,
)
from agac_tpu.cloudprovider.aws.driver import (
    accelerator_name,
    parent_domain,
    replace_wildcards,
)
from agac_tpu.cloudprovider.aws.load_balancer import get_lb_name_from_hostname
from agac_tpu.cloudprovider.aws.sigv4 import Credentials, sign_request
from agac_tpu.cluster.objects import ObjectMeta
from agac_tpu.cluster.serde import from_wire, to_wire
from agac_tpu.reconcile import RateLimitingQueue
from agac_tpu.sharding import HashRing, transition_plan

# ---------------------------------------------------------------------------
# serde round trip
# ---------------------------------------------------------------------------

IDENT = st.text(alphabet="abcdefghijklmnopqrstuvwxyz0123456789-.", min_size=1, max_size=20)
FREE_TEXT = st.text(max_size=30)  # arbitrary unicode values
STR_DICT = st.dictionaries(IDENT, FREE_TEXT, max_size=4)

METAS = st.builds(
    ObjectMeta,
    name=IDENT,
    namespace=IDENT,
    uid=st.text(max_size=12),
    resource_version=st.text(alphabet="0123456789", max_size=6),
    generation=st.integers(min_value=0, max_value=10**6),
    creation_timestamp=st.none() | st.text(max_size=24),
    deletion_timestamp=st.none() | st.text(max_size=24),
    annotations=STR_DICT,
    labels=STR_DICT,
    finalizers=st.lists(IDENT, max_size=3),
)

SPECS = st.builds(
    EndpointGroupBindingSpec,
    endpoint_group_arn=FREE_TEXT,
    client_ip_preservation=st.booleans(),
    weight=st.none() | st.integers(min_value=0, max_value=255),
    service_ref=st.none() | st.builds(ServiceReference, name=IDENT),
    ingress_ref=st.none() | st.builds(IngressReference, name=IDENT),
)

STATUSES = st.builds(
    EndpointGroupBindingStatus,
    endpoint_ids=st.lists(FREE_TEXT, max_size=4),
    observed_generation=st.integers(min_value=0, max_value=10**6),
)

BINDINGS = st.builds(
    EndpointGroupBinding, metadata=METAS, spec=SPECS, status=STATUSES
)


@given(BINDINGS)
def test_serde_round_trip_is_lossless(obj):
    assert from_wire(EndpointGroupBinding, to_wire(obj)) == obj


@given(BINDINGS, st.dictionaries(st.text(min_size=1, max_size=10), FREE_TEXT, max_size=3))
def test_serde_ignores_unknown_wire_keys(obj, extra):
    """Forward compatibility: unknown keys (a NEWER server's fields)
    must not break decode or leak into the object."""
    wire = to_wire(obj)
    known = set(wire)
    wire.update({k: v for k, v in extra.items() if k not in known})
    assert from_wire(EndpointGroupBinding, wire) == obj


# ---------------------------------------------------------------------------
# LB hostname parser
# ---------------------------------------------------------------------------

LB_NAME = st.from_regex(r"[a-z0-9][a-z0-9-]{0,18}", fullmatch=True)
LB_HASH = st.from_regex(r"[a-z0-9]{4,16}", fullmatch=True)
REGION = st.from_regex(r"[a-z]{2}-[a-z]{4,9}-[1-9]", fullmatch=True)


@given(LB_NAME, LB_HASH, REGION)
def test_public_alb_hostname_round_trips(name, lb_hash, region):
    assume(not name.startswith("internal-"))
    hostname = f"{name}-{lb_hash}.{region}.elb.amazonaws.com"
    assert get_lb_name_from_hostname(hostname) == (name, region)


@given(LB_NAME, LB_HASH, REGION)
def test_internal_alb_hostname_round_trips(name, lb_hash, region):
    hostname = f"internal-{name}-{lb_hash}.{region}.elb.amazonaws.com"
    assert get_lb_name_from_hostname(hostname) == (name, region)


@given(LB_NAME, LB_HASH, REGION)
def test_nlb_hostname_round_trips(name, lb_hash, region):
    hostname = f"{name}-{lb_hash}.elb.{region}.amazonaws.com"
    assert get_lb_name_from_hostname(hostname) == (name, region)


@given(st.text(max_size=60))
def test_parser_raises_only_valueerror_on_garbage(hostname):
    """The controllers catch ValueError and emit a permanent-failure
    Event; any OTHER exception type would crash into the retry loop."""
    try:
        name, region = get_lb_name_from_hostname(hostname)
    except ValueError:
        return
    assert isinstance(name, str) and isinstance(region, str)


# ---------------------------------------------------------------------------
# SigV4 canonicalization
# ---------------------------------------------------------------------------

CREDS = Credentials("AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY")
NOW = datetime.datetime(2015, 8, 30, 12, 36, 0, tzinfo=datetime.timezone.utc)
HEADER_NAME = st.from_regex(r"X-[A-Za-z][A-Za-z0-9-]{0,10}", fullmatch=True)
HEADER_VALUE = st.text(
    alphabet=st.characters(min_codepoint=33, max_codepoint=126), min_size=1, max_size=12
)


@given(st.dictionaries(HEADER_NAME, HEADER_VALUE, max_size=4), st.randoms())
@settings(suppress_health_check=[HealthCheck.too_slow])
def test_sigv4_signature_invariant_to_header_order_and_case(headers, rnd):
    """AWS canonicalizes headers (lowercase, sorted) before signing:
    the signature must not depend on dict order or name casing."""
    assume(len({k.lower() for k in headers}) == len(headers))
    base = sign_request(
        "POST", "https://example.amazonaws.com/", dict(headers), b"body",
        "service", "us-east-1", CREDS, now=NOW,
    )
    items = list(headers.items())
    rnd.shuffle(items)
    recased = {
        "".join(c.upper() if rnd.random() < 0.5 else c.lower() for c in k): v
        for k, v in items
    }
    permuted = sign_request(
        "POST", "https://example.amazonaws.com/", recased, b"body",
        "service", "us-east-1", CREDS, now=NOW,
    )
    assert base["Authorization"] == permuted["Authorization"]


# ---------------------------------------------------------------------------
# workqueue semantics
# ---------------------------------------------------------------------------

# each example spins up a queue (one daemon waker thread): keep the
# example count bounded so the tier stays fast
QUEUE_SETTINGS = settings(
    max_examples=25, suppress_health_check=[HealthCheck.too_slow], deadline=None
)


@given(st.lists(st.sampled_from("abcde"), min_size=1, max_size=40))
@QUEUE_SETTINGS
def test_queue_dedups_and_delivers_each_key_once(keys):
    queue = RateLimitingQueue(name="prop-dedup")
    try:
        for key in keys:
            queue.add(key)
        assert len(queue) <= len(set(keys))
        delivered = []
        while len(queue):
            item, shutdown = queue.get(timeout=1.0)
            assert not shutdown
            delivered.append(item)
            queue.done(item)
        assert sorted(delivered) == sorted(set(keys))
    finally:
        queue.shutdown()


@given(
    st.lists(st.sampled_from(["add-a", "add-b", "get", "done"]), min_size=1, max_size=60)
)
@QUEUE_SETTINGS
def test_no_key_is_processed_by_two_workers(ops):
    """client-go's Type contract: an item being processed is never
    handed out again until done(); a re-add during processing means
    exactly one more delivery afterwards."""
    queue = RateLimitingQueue(name="prop-excl")
    in_flight: list[str] = []
    try:
        for op in ops:
            if op.startswith("add-"):
                queue.add(op[-1])
            elif op == "get":
                item, _ = queue.get(timeout=0.05)
                if item is not None:
                    assert item not in in_flight, "item handed to two workers"
                    in_flight.append(item)
            elif op == "done" and in_flight:
                queue.done(in_flight.pop(0))
    finally:
        queue.shutdown()


@given(st.sampled_from("ab"), st.integers(min_value=1, max_value=5))
@QUEUE_SETTINGS
def test_readd_while_processing_delivers_exactly_once_more(key, readds):
    queue = RateLimitingQueue(name="prop-readd")
    try:
        queue.add(key)
        item, _ = queue.get(timeout=1.0)
        assert item == key
        for _ in range(readds):
            queue.add(key)  # dirty while processing: not ready yet
        assert len(queue) == 0
        queue.done(key)  # dirty -> requeued once
        item, _ = queue.get(timeout=1.0)
        assert item == key
        queue.done(key)
        assert len(queue) == 0
    finally:
        queue.shutdown()


# ---------------------------------------------------------------------------
# small total functions
# ---------------------------------------------------------------------------


@given(IDENT, IDENT, st.text(min_size=1, max_size=300))
def test_accelerator_name_clamp_is_total_bounded_deterministic(resource, ns, name):
    obj = SimpleNamespace(
        metadata=SimpleNamespace(namespace=ns, name=name, annotations={})
    )
    first = accelerator_name(resource, obj)
    assert accelerator_name(resource, obj) == first
    assert 0 < len(first) <= 64
    raw = f"{resource}-{ns}-{name}"
    if len(raw) <= 64:
        assert first == raw


# ---------------------------------------------------------------------------
# webhook robustness
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def webhook_url():
    import threading

    from agac_tpu.webhook import make_server

    srv = make_server(0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/validate-endpointgroupbinding"
    srv.shutdown()
    srv.server_close()


@given(st.binary(max_size=300))
@settings(
    max_examples=50, deadline=None,
    suppress_health_check=[HealthCheck.function_scoped_fixture, HealthCheck.too_slow],
)
def test_webhook_never_5xxs_on_garbage_bodies(webhook_url, body):
    """The apiserver calls this endpoint with failurePolicy=Fail: a
    5xx (an unhandled exception) blocks ALL binding writes cluster-
    wide.  Arbitrary junk must map to a 4xx denial or a parsed 200,
    never a server error."""
    import urllib.error
    import urllib.request

    request = urllib.request.Request(
        webhook_url,
        data=body,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=5) as response:
            status = response.status
    except urllib.error.HTTPError as err:
        status = err.code
    assert status < 500, f"webhook 5xx'd on garbage body: {status}"


@given(st.text(alphabet="abcdef.-", max_size=40))
def test_parent_domain_walk_terminates(hostname):
    steps = 0
    while hostname:
        hostname = parent_domain(hostname)
        steps += 1
        assert steps <= 41, "parent-domain walk did not shrink"


@given(st.text(max_size=30))
def test_replace_wildcards_replaces_at_most_first_escape(s):
    out = replace_wildcards(s)
    assert out.count("\\052") == max(0, s.count("\\052") - 1)
    if "\\052" not in s:
        assert out == s


# ---------------------------------------------------------------------------
# elastic ring resize (ISSUE 10): movement bounds, vnode identity,
# post-resize balance — the properties the drain/handoff protocol's
# cost model is built on
# ---------------------------------------------------------------------------

# the resize path the rollout runbook walks: grow 1→2→4→8, scale back
# to 4 — every step's movement must stay consistent-hashing-bounded
RESIZE_CHAIN = (1, 2, 4, 8, 4)


@given(st.integers(min_value=0, max_value=2**31 - 1))
@settings(max_examples=25, deadline=None)
def test_resize_chain_movement_bounded_by_one_nth_plus_slack(seed):
    keys = [f"ns{seed % 7}/svc-{seed}-{i:05d}" for i in range(600)]
    for old_count, new_count in zip(RESIZE_CHAIN, RESIZE_CHAIN[1:]):
        old, new = HashRing(old_count), HashRing(new_count)
        moved = sum(
            1 for k in keys if old.shard_for_key(k) != new.shard_for_key(k)
        )
        if new_count > old_count:
            # growth: ideal movement is (new-old)/new of the keyspace
            ideal = (new_count - old_count) / new_count
        else:
            # shrink: the removed shards' arcs move, (old-new)/old
            ideal = (old_count - new_count) / old_count
        # vnode-placement variance + finite sample slack; a modulo
        # partitioner would move ~(1 - 1/max) and blow this bound
        assert moved / len(keys) <= ideal + 0.2, (
            f"{old_count}->{new_count} moved {moved}/{len(keys)} "
            f"(ideal {ideal:.2f})"
        )
        # and the exact arc measure stays consistent-hash bounded too
        plan = transition_plan(old, new)
        assert plan.moved_fraction <= ideal + 0.15


@given(
    st.integers(min_value=1, max_value=12),
    st.integers(min_value=1, max_value=12),
    st.text(alphabet="abcdefghij0123456789-", min_size=1, max_size=24),
)
@settings(max_examples=60, deadline=None)
def test_surviving_vnode_identity_pins_non_moving_keys(old_count, new_count, name):
    """A key whose shard SURVIVES the resize and does not fall in a
    re-captured arc keeps its shard index: surviving vnodes are
    identical points on both rings, so ownership is stable unless the
    transition plan says the key's arc moved."""
    assume(old_count != new_count)
    old, new = HashRing(old_count), HashRing(new_count)
    plan = transition_plan(old, new)
    key = f"default/{name}"
    if not plan.key_moves(key):
        assert old.shard_for_key(key) == new.shard_for_key(key)
    else:
        s_old, s_new = old.shard_for_key(key), new.shard_for_key(key)
        assert s_new in plan.gainers_of[s_old]


@given(st.sampled_from([2, 3, 4, 5, 8]))
@settings(max_examples=10, deadline=None)
def test_post_resize_distribution_stays_balanced(new_count):
    """After any resize in the chain, the max/min shard-load ratio of
    the NEW ring stays bounded — a transition never leaves a pathological
    hot shard behind."""
    keys = [f"default/svc-{i:05d}" for i in range(4000)]
    ring = HashRing(new_count)
    buckets = ring.partition(keys)
    sizes = [len(owned) for owned in buckets.values()]
    assert min(sizes) > 0
    fair = len(keys) / new_count
    assert max(sizes) <= 1.7 * fair
    assert min(sizes) >= 0.45 * fair
    assert max(sizes) / min(sizes) <= 3.2
