"""Unit tier for the deterministic simulation runtime (ISSUE 7):
``agac_tpu/sim/runtime.py`` ordering/coalescing/trace semantics, the
clock-seam install contract, the harness's deterministic cooperative
thread-step order, and one soak scenario ported from the wall-clock
tier (``test_soak_e2e.py``) that must finish in seconds of wall time.
"""

from __future__ import annotations

import time

import pytest

from agac_tpu import clockseam
from agac_tpu.sim import runtime
from agac_tpu.sim.runtime import SIM_EPOCH, SimScheduler


# ---------------------------------------------------------------------------
# virtual-time ordering
# ---------------------------------------------------------------------------


class TestVirtualTimeOrdering:
    def test_events_fire_in_deadline_order_and_jump_the_clock(self):
        sched = SimScheduler()
        fired = []
        sched.call_at(30.0, lambda: fired.append(("c", sched.now)), "c")
        sched.call_at(10.0, lambda: fired.append(("a", sched.now)), "a")
        sched.call_at(20.0, lambda: fired.append(("b", sched.now)), "b")
        while sched.step():
            pass
        assert fired == [("a", 10.0), ("b", 20.0), ("c", 30.0)]
        assert sched.now == 30.0

    def test_equal_deadline_ties_break_by_registration_order(self):
        sched = SimScheduler()
        fired = []
        for name in ("first", "second", "third"):
            sched.call_at(5.0, lambda n=name: fired.append(n), name)
        while sched.step():
            pass
        assert fired == ["first", "second", "third"]

    def test_priority_orders_same_instant_events(self):
        sched = SimScheduler()
        fired = []
        sched.call_at(5.0, lambda: fired.append("late"), "late", priority=2)
        sched.call_at(5.0, lambda: fired.append("early"), "early", priority=0)
        while sched.step():
            pass
        assert fired == ["early", "late"]

    def test_sleep_advances_time_in_place_without_dispatch(self):
        sched = SimScheduler()
        observed = []

        def busy():
            sched.clock.sleep(7.0)  # holds its "core" for 7 virtual s
            observed.append(("busy-done", sched.now))

        sched.call_at(1.0, busy, "busy")
        sched.call_at(3.0, lambda: observed.append(("timer", sched.now)), "timer")
        while sched.step():
            pass
        # the timer due at t=3 could not preempt the sleeping event; it
        # fired after the busy event returned, at the advanced clock
        assert observed == [("busy-done", 8.0), ("timer", 8.0)]

    def test_monotonic_and_wall_views_share_one_clock(self):
        sched = SimScheduler()
        sched.consume(42.0)
        assert sched.monotonic() == 42.0
        assert sched.time() == SIM_EPOCH + 42.0
        assert sched.clock.monotonic() == 42.0
        assert sched.clock.time() == SIM_EPOCH + 42.0

    def test_call_at_in_the_past_is_clamped_to_now(self):
        sched = SimScheduler()
        sched.consume(100.0)
        fired = []
        sched.call_at(5.0, lambda: fired.append(sched.now), "stale")
        assert sched.step()
        assert fired == [100.0]

    def test_cancelled_events_never_fire(self):
        sched = SimScheduler()
        fired = []
        event = sched.call_after(1.0, lambda: fired.append("no"), "cancelled")
        sched.call_after(2.0, lambda: fired.append("yes"), "kept")
        event.cancel()
        while sched.step():
            pass
        assert fired == ["yes"]
        assert sched.next_deadline() is None


# ---------------------------------------------------------------------------
# timer coalescing
# ---------------------------------------------------------------------------


class TestTimerCoalescing:
    def test_recurring_timer_slept_past_fires_once_then_reanchors(self):
        sched = SimScheduler()
        ticks = []
        sched.every(10.0, lambda: ticks.append(sched.now), "tick")

        def long_sleeper():
            sched.clock.sleep(3600.0)  # sleeps past 360 periods

        sched.call_at(5.0, long_sleeper, "sleeper")
        # run out five dispatches: sleeper, then coalesced ticks
        for _ in range(4):
            sched.step()
        # one tick at 3605 (the 360 missed periods collapsed), then
        # re-anchored from now: 3615, 3625
        assert ticks == [3605.0, 3615.0, 3625.0]

    def test_recurring_timer_steady_cadence_without_drift(self):
        sched = SimScheduler()
        ticks = []
        sched.every(2.5, lambda: ticks.append(sched.now), "tick")
        for _ in range(4):
            sched.step()
        assert ticks == [2.5, 5.0, 7.5, 10.0]

    def test_first_after_overrides_initial_delay(self):
        sched = SimScheduler()
        ticks = []
        sched.every(100.0, lambda: ticks.append(sched.now), "tick", first_after=1.0)
        sched.step()
        sched.step()
        assert ticks == [1.0, 101.0]

    def test_cancel_stops_recurrence(self):
        sched = SimScheduler()
        ticks = []
        event = sched.every(1.0, lambda: ticks.append(sched.now), "tick")
        sched.step()
        event.cancel()
        assert not sched.step()
        assert ticks == [1.0]

    def test_zero_interval_rejected(self):
        sched = SimScheduler()
        with pytest.raises(ValueError):
            sched.every(0.0, lambda: None, "bad")


# ---------------------------------------------------------------------------
# cooperative actors
# ---------------------------------------------------------------------------


class TestActors:
    def test_actor_steps_interleave_with_timers_deterministically(self):
        sched = SimScheduler()
        log = []

        def actor():
            log.append(("actor", sched.now))
            yield 4.0
            log.append(("actor", sched.now))
            yield 4.0
            log.append(("actor", sched.now))

        sched.spawn(actor(), "actor")
        timer = sched.every(3.0, lambda: log.append(("timer", sched.now)), "timer")
        while sched.step() and sched.now < 8.0:
            pass
        timer.cancel()
        assert log == [
            ("actor", 0.0),
            ("timer", 3.0),
            ("actor", 4.0),
            ("timer", 6.0),
            ("actor", 8.0),
        ]


# ---------------------------------------------------------------------------
# the event-trace hash (replay contract)
# ---------------------------------------------------------------------------


class TestTraceHash:
    @staticmethod
    def _scenario(order):
        sched = SimScheduler()
        for delay, name in order:
            sched.call_after(delay, lambda: None, name)
        while sched.step():
            pass
        return sched.trace_hash()

    def test_identical_runs_hash_identically(self):
        order = [(1.0, "a"), (2.0, "b"), (3.0, "c")]
        assert self._scenario(order) == self._scenario(order)

    def test_different_interleaving_hashes_differently(self):
        assert self._scenario([(1.0, "a"), (2.0, "b")]) != self._scenario(
            [(2.0, "a"), (1.0, "b")]
        )

    def test_sleeps_and_app_records_fold_into_the_hash(self):
        def run(with_record):
            sched = SimScheduler()
            sched.call_after(1.0, lambda: sched.clock.sleep(2.0), "s")
            while sched.step():
                pass
            if with_record:
                sched.record("work", "controller:key")
            return sched.trace_hash()

        assert run(True) != run(False)

    def test_trace_tail_keeps_recent_lines(self):
        sched = SimScheduler()
        sched.call_after(1.0, lambda: None, "evt")
        sched.step()
        assert any("evt" in line for line in sched.trace_tail)


# ---------------------------------------------------------------------------
# the clock-seam install contract
# ---------------------------------------------------------------------------


class TestInstalledSeam:
    def test_installed_routes_seam_to_virtual_clock_and_resets(self):
        sched = SimScheduler()
        sched.consume(11.0)
        assert clockseam.threads_enabled()
        with runtime.installed(sched):
            assert clockseam.monotonic() == 11.0
            assert clockseam.time() == SIM_EPOCH + 11.0
            assert not clockseam.threads_enabled()
            clockseam.sleep(4.0)  # advances virtual time, returns instantly
            assert clockseam.monotonic() == 15.0
        assert clockseam.threads_enabled()
        # real clock restored: two reads make progress without sleep
        assert clockseam.monotonic() != 15.0

    def test_installed_resets_on_exception(self):
        sched = SimScheduler()
        with pytest.raises(RuntimeError):
            with runtime.installed(sched):
                raise RuntimeError("boom")
        assert clockseam.threads_enabled()


# ---------------------------------------------------------------------------
# harness-level determinism + the ported soak scenario
# ---------------------------------------------------------------------------


def _soak_world(churn_ops=40, slots=8):
    """One small churned world (the ported soak shape): returns the
    harness stats + oracle verdicts + trace hash."""
    import random

    from agac_tpu.sim import fuzz
    from agac_tpu.sim.harness import SimHarness, SimHarnessConfig
    from agac_tpu.sim.oracles import standard_oracles

    rng = random.Random(20260804)
    config = SimHarnessConfig(quota_accelerators=slots + 10)
    with SimHarness(config=config) as harness:
        for i in range(slots):
            harness.aws.add_load_balancer(
                f"lb{i}", "us-west-2", fuzz._nlb_hostname(i)
            )
        harness.aws.add_hosted_zone("example.com")
        harness.run_for(10.0)  # leadership + initial sync
        live: set[str] = set()
        for _ in range(churn_ops):
            slot = rng.randrange(slots)
            name = f"svc{slot}"
            if name not in live:
                harness.cluster.create(
                    "Service", fuzz._make_service(name, slot, slot % 2 == 0)
                )
                live.add(name)
            elif rng.random() < 0.4:
                harness.cluster.delete("Service", "default", name)
                live.discard(name)
            else:
                obj = harness.cluster.get("Service", "default", name)
                obj.metadata.labels["touched"] = str(rng.randrange(1 << 30))
                harness.cluster.update("Service", obj)
            harness.run_for(rng.uniform(5.0, 40.0))
        assert harness.run_until_quiescent(3600.0, settle_window=60.0)
        return standard_oracles(harness), harness.trace_hash(), harness.stats()


class TestHarnessDeterminism:
    def test_ported_soak_scenario_converges_fast(self):
        start = time.monotonic()
        violations, _, stats = _soak_world()
        wall = time.monotonic() - start
        assert violations == []
        # the wall-clock soak needs minutes; the ported scenario rides
        # hundreds of virtual minutes in single-digit wall seconds
        assert wall < 5.0, f"ported soak took {wall:.1f}s wall"
        assert stats["virtual_time"] > 300.0

    def test_thread_step_order_is_deterministic_across_runs(self):
        # the whole manager-on-virtual-time scenario — informer pumps,
        # round-robin worker steps, settle polls, elector ticks —
        # replays to the identical event-trace hash
        first = _soak_world()
        second = _soak_world()
        assert first[1] == second[1]
        assert first[2]["aws_calls"] == second[2]["aws_calls"]
