"""Sim-harness tiers for the horizontal sharding plane (ISSUE 8):
two-shard fleets of concurrently-LIVE replicas on virtual time.

Fast tier (tier-1): balanced two-shard convergence, the
shard-lease-failover drill (kill one replica; the survivor steals the
expired lease, adopts the orphaned keyspace via the reshard resync,
and converges under the full oracle battery plus the new
exclusive-ownership oracle), graceful handover, crash-at-API-boundary
recovery, sim quota division, byte-identical replay, and the
oracle-catches-overlap canary.

Slow tier (the CI ``sim`` job): the acceptance soak — N=50k services
across two shards with a mid-run shard failover, deterministic from
seed (the replay identity is pinned by the fast tier; the soak pins
scale and the oracle battery).
"""

from __future__ import annotations

import time

import pytest

from agac_tpu.cloudprovider.aws.health import HealthConfig
from agac_tpu.leaderelection import LeaderElectionConfig
from agac_tpu.observability.metrics import parse_text
from agac_tpu.sim import fuzz
from agac_tpu.sim.harness import SimHarness, SimHarnessConfig
from agac_tpu.sim.oracles import (
    check_exclusive_shard_ownership,
    check_resize_handoffs,
    check_slo,
    standard_oracles,
)

from .fixtures import NLB_HOSTNAME, NLB_NAME, NLB_REGION, make_lb_service
from .test_chaos_e2e import nlb_hostname

LEASE = LeaderElectionConfig(
    lease_duration=60.0, renew_deadline=15.0, retry_period=5.0
)


def sharded_config(**overrides) -> SimHarnessConfig:
    defaults = dict(
        replicas=2,
        shard_count=2,
        # capacity 2 so the survivor CAN adopt the whole keyspace;
        # the one-claim-per-tick rule still balances the start 1+1
        shards_per_replica=2,
        lease=LEASE,
    )
    defaults.update(overrides)
    return SimHarnessConfig(**defaults)


def seed_fleet(harness, n: int) -> None:
    harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
    for i in range(n):
        harness.cluster.create("Service", make_lb_service(name=f"svc-{i:05d}"))


def converge(harness, timeout=7200.0):
    harness.run_for(30.0)
    assert harness.run_until_quiescent(timeout, settle_window=60.0), (
        f"world still busy: {harness.stats()}"
    )


class TestTwoShardConvergence:
    def test_fleet_splits_across_replicas_and_converges(self):
        with SimHarness(config=sharded_config()) as harness:
            seed_fleet(harness, 40)
            converge(harness)
            ownership = harness.shard_ownership()
            assert sorted(
                shard for owned in ownership.values() for shard in owned
            ) == [0, 1]
            assert all(len(owned) == 1 for owned in ownership.values()), (
                "one-claim-per-tick must balance two replicas 1+1"
            )
            assert len(harness.aws.all_accelerator_arns()) == 40
            assert standard_oracles(harness) == []
            # a second wave AFTER ownership settled: these arrive as
            # ordinary spec journeys (the t=0 fleet was adopted via
            # the startup handoff resync), so the SLO oracle below is
            # non-vacuous
            for i in range(40, 50):
                harness.cluster.create(
                    "Service", make_lb_service(name=f"svc-{i:05d}")
                )
            converge(harness)
            # the convergence-SLO oracle (ISSUE 9): a fault-free
            # two-shard fleet must meet every objective, measured over
            # the fleet-scoped journey tracker
            assert check_slo(harness) == []
            assert harness.journey.converged_total == 50
            assert harness.journey.inflight() == 0
            by_name = {
                o["name"]: o for o in harness.slo_engine.status()["objectives"]
            }
            assert by_name["ga_converge_p99"]["journeys"] >= 10
            assert by_name["ga_converge_p99"]["healthy"] is True

    def test_sim_replica_registries_are_isolated(self):
        """The metrics-isolation regression (ISSUE 9 satellite):
        concurrently-live sim replicas carry PRIVATE per-world
        registries — each reports only its own agac_shard_keys_owned,
        the two are disjoint slices of the fleet, and the fleet-merge
        layer (not registry sharing) is what produces the one fleet
        view, with gauges labeled by shard."""
        with SimHarness(config=sharded_config()) as harness:
            seed_fleet(harness, 40)
            converge(harness)
            owned = {}
            for replica in harness.live_replicas():
                samples = parse_text(replica.world.registry.render())
                owned[replica.identity] = samples["agac_shard_keys_owned"]
            assert sum(owned.values()) == 40
            assert all(count > 0 for count in owned.values()), owned
            # world registries never share series: each replica's
            # registry carries exactly ONE keys-owned series (its own)
            for replica in harness.live_replicas():
                series = [
                    name
                    for name in parse_text(replica.world.registry.render())
                    if name.startswith("agac_shard_keys_owned")
                ]
                assert series == ["agac_shard_keys_owned"], series
            # the merged fleet view labels them by shard instead of
            # folding them together
            fleet_samples = parse_text(harness.fleet_metrics())
            for identity, count in owned.items():
                assert (
                    fleet_samples[f'agac_shard_keys_owned{{shard="{identity}"}}']
                    == count
                )
            assert "agac_shard_keys_owned" not in fleet_samples
            # and the summed journey histograms cover the whole fleet
            # (the t=0 fleet arrives via the startup handoff adoption)
            total = sum(
                value
                for name, value in fleet_samples.items()
                if name.startswith("agac_journey_converge_seconds_count")
            )
            assert total == 40

    def test_both_replicas_did_real_work(self):
        """The point of sharding: BOTH replicas reconcile — each owns
        a non-trivial slice of the keyspace."""
        with SimHarness(config=sharded_config()) as harness:
            seed_fleet(harness, 40)
            converge(harness)
            depths = []
            for stack in harness.live_stacks():
                manager = stack.manager
                keys = manager._count_owned_keys()
                depths.append(keys)
            assert sum(depths) == 40
            assert all(keys >= 5 for keys in depths), depths

    def test_sim_quota_division_sums_to_global(self):
        global_qps = 40.0
        config = sharded_config(
            health=HealthConfig(aimd_qps=global_qps, min_calls=1000)
        )
        with SimHarness(config=config) as harness:
            seed_fleet(harness, 20)
            converge(harness)
            ceilings = [
                replica.world.health.service("globalaccelerator").limiter.ceiling()
                for replica in harness.live_replicas()
            ]
            assert ceilings == [global_qps / 2, global_qps / 2]
            assert sum(ceilings) <= global_qps


class TestShardFailover:
    def test_kill_replica_survivor_steals_adopts_converges(self):
        """The drill the ISSUE names: kill one replica mid-fleet; the
        survivor steals the expired shard lease, adopts the orphaned
        keyspace (reshard resync — those keys' events died with the
        victim), takes over the victim's quota slice, and the world
        converges under every oracle including exclusive ownership."""
        global_qps = 40.0
        config = sharded_config(
            health=HealthConfig(aimd_qps=global_qps, min_calls=1000)
        )
        with SimHarness(config=config) as harness:
            seed_fleet(harness, 30)
            harness.run_for(30.0)
            killed = harness.kill_shard_replica()
            # keys created AFTER the kill, in the dead replica's former
            # keyspace, must be picked up by the survivor post-steal
            for i in range(30, 40):
                harness.cluster.create(
                    "Service", make_lb_service(name=f"svc-{i:05d}")
                )
            harness.run_for(LEASE.lease_duration + 3 * LEASE.retry_period)
            ownership = harness.shard_ownership()
            assert list(ownership) == [
                replica.identity for replica in harness.live_replicas()
            ]
            survivor_owned = next(iter(ownership.values()))
            assert survivor_owned == frozenset({0, 1}), (
                f"survivor must steal {killed}'s lease: {ownership}"
            )
            converge(harness)
            assert len(harness.aws.all_accelerator_arns()) == 40
            assert standard_oracles(harness) == []
            # the victim's quota slice moved with its lease
            survivor = harness.live_replicas()[0]
            assert survivor.world.health.service(
                "globalaccelerator"
            ).limiter.ceiling() == pytest.approx(global_qps)

    def test_graceful_stop_hands_over_without_lease_wait(self):
        with SimHarness(config=sharded_config()) as harness:
            seed_fleet(harness, 10)
            harness.run_for(30.0)
            harness.stop_shard_replica()
            # released leases are claimable immediately: well under one
            # lease_duration the survivor owns everything
            harness.run_for(3 * LEASE.retry_period)
            ownership = harness.shard_ownership()
            assert list(ownership.values()) == [frozenset({0, 1})]
            converge(harness)
            assert standard_oracles(harness) == []

    def test_crash_at_api_boundary_kills_only_that_replica(self):
        """A SimulatedCrash raised inside one replica's worker is that
        replica's process death: its stack vanishes, its leases stay
        held, the pool is replenished, and the fleet still converges."""
        with SimHarness(config=sharded_config()) as harness:
            harness.aws.add_load_balancer(NLB_NAME, NLB_REGION, NLB_HOSTNAME)
            harness.fault_plan.crash("create_listener", when="before")
            for i in range(20):
                harness.cluster.create(
                    "Service", make_lb_service(name=f"svc-{i:05d}")
                )
            harness.run_for(LEASE.lease_duration + 5 * LEASE.retry_period)
            converge(harness)
            assert len(harness.aws.all_accelerator_arns()) == 20
            assert standard_oracles(harness) == []

    def test_replay_is_byte_identical(self):
        def run():
            with SimHarness(config=sharded_config()) as harness:
                seed_fleet(harness, 25)
                harness.run_for(30.0)
                harness.kill_shard_replica()
                harness.run_until_quiescent(7200.0, settle_window=60.0)
                return harness.trace_hash(), len(harness.aws.all_accelerator_arns())

        first, second = run(), run()
        assert first == second
        assert first[1] == 25


class TestLiveResize:
    """The elastic resharding plane (ISSUE 10): a mid-run 2→4 live
    resize on virtual time — drain/handoff-mediated, exclusive
    ownership held *throughout*, journeys tracked per re-home."""

    def test_mid_run_2_to_4_resize_converges_under_oracles(self):
        from agac_tpu.sharding import transition_plan, HashRing

        config = sharded_config(shards_per_replica=4)
        with SimHarness(config=config) as harness:
            seed_fleet(harness, 40)
            converge(harness)
            assert harness.resize_settled(2)
            converged_before = harness.journey.converged_total
            # the live resize: replicas observe the ring lease on
            # their next membership tick and run the drain/handoff
            harness.request_resize(4)
            # spec edits DURING the transition must keep converging
            for i in range(40, 48):
                harness.cluster.create(
                    "Service", make_lb_service(name=f"svc-{i:05d}")
                )
            harness.run_for(LEASE.lease_duration + 6 * LEASE.retry_period)
            assert harness.resize_settled(4), harness.resize_states()
            converge(harness)
            # the full battery INCLUDING the key-level exclusive
            # ownership sweep armed through the transition and the
            # handoff-window oracle
            assert standard_oracles(harness) == []
            assert check_resize_handoffs(harness) == []
            assert harness.violations == []
            # every shard of the new ring is held and the fleet is
            # whole — no duplicates, no lost keys
            held = sorted(
                shard
                for owned in harness.shard_ownership().values()
                for shard in owned
            )
            assert held == [0, 1, 2, 3]
            assert len(harness.aws.all_accelerator_arns()) == 48
            # moved-key bound: the 2→4 plan re-homes about half the
            # ring (2 of 4 shards are new) and NEVER more than the
            # arc measure + slack — the property tier pins tighter
            # bounds per step; here the measured fleet must agree
            plan = transition_plan(HashRing(2), HashRing(4))
            keys = [f"default/svc-{i:05d}" for i in range(48)]
            moved = sum(1 for key in keys if plan.key_moves(key))
            assert moved / len(keys) <= plan.moved_fraction + 0.2
            # re-homed journeys: the resize resync opened journeys on
            # the RESIZE trigger and every one of them converged
            from agac_tpu.observability.metrics import parse_text

            samples = parse_text(harness.fleet_metrics())
            resize_count = sum(
                value
                for name, value in samples.items()
                if name.startswith("agac_journey_converge_seconds_count")
                and 'trigger="resize"' in name
            )
            assert resize_count >= moved, (
                f"only {resize_count} resize journeys for {moved} moved keys"
            )
            assert harness.journey.inflight() == 0
            assert harness.journey.converged_total > converged_before

    def test_resize_with_mid_transition_kill_completes(self):
        """Resize composed with a crash: one replica dies mid-
        transition (kill -9 semantics — its leases stay held); the
        survivor steals them, self-drains/adopts, and COMPLETES the
        transition.  The handoff oracle excuses the dead holder's
        window (failover latency), but exclusivity must still hold."""
        config = sharded_config(shards_per_replica=4)
        with SimHarness(config=config) as harness:
            seed_fleet(harness, 30)
            converge(harness)
            harness.request_resize(4)
            # let the transition start, then kill one replica
            harness.run_for(2 * LEASE.retry_period)
            harness.kill_shard_replica()
            harness.run_for(2 * (LEASE.lease_duration + 6 * LEASE.retry_period))
            assert harness.resize_settled(4), harness.resize_states()
            converge(harness)
            assert standard_oracles(harness) == []
            assert harness.violations == []
            survivor = harness.live_replicas()[0]
            assert survivor.stack.manager.shard_membership.owned_shards() == (
                frozenset({0, 1, 2, 3})
            )
            assert len(harness.aws.all_accelerator_arns()) == 30

    def test_shrink_4_to_2_releases_obsolete_leases(self):
        config = sharded_config(
            shard_count=4, replicas=2, shards_per_replica=4
        )
        with SimHarness(config=config) as harness:
            seed_fleet(harness, 24)
            converge(harness)
            harness.request_resize(2)
            harness.run_for(LEASE.lease_duration + 6 * LEASE.retry_period)
            assert harness.resize_settled(2), harness.resize_states()
            converge(harness)
            assert standard_oracles(harness) == []
            held = sorted(
                shard
                for owned in harness.shard_ownership().values()
                for shard in owned
            )
            assert held == [0, 1], "obsolete leases must be released"
            assert len(harness.aws.all_accelerator_arns()) == 24

    def test_resize_replay_is_byte_identical(self):
        def run():
            config = sharded_config(shards_per_replica=4)
            with SimHarness(config=config) as harness:
                seed_fleet(harness, 20)
                harness.run_for(30.0)
                harness.request_resize(4)
                harness.run_until_quiescent(7200.0, settle_window=60.0)
                return harness.trace_hash(), harness.resize_settled(4)

        first, second = run(), run()
        assert first == second
        assert first[1] is True


class TestExclusiveOwnershipOracle:
    def test_oracle_catches_forced_overlap(self):
        """A canary for the oracle itself: force two live memberships
        to claim the same shard and the violation must surface —
        an oracle that can't fail proves nothing."""
        with SimHarness(config=sharded_config()) as harness:
            seed_fleet(harness, 4)
            harness.run_for(30.0)
            for replica in harness.live_replicas():
                replica.stack.manager.shard_membership._publish({0, 1})
            harness.check_exclusive_ownership()
            violations = check_exclusive_shard_ownership(harness)
            assert violations, "forced overlap must be caught"
            assert any("owned by BOTH" in v for v in violations)


# ---------------------------------------------------------------------------
# the acceptance soak: N=50k, two shards, mid-run failover (CI sim job)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestTwoShardSoak:
    def test_fifty_k_two_shard_soak_with_failover(self):
        n = 50_000
        start_wall = time.monotonic()
        config = sharded_config(
            resync_period=6 * 3600.0,
            settle_poll_interval=30.0,
            discovery_ttl=300.0,
            quota_accelerators=n + 50,
            lease=LeaderElectionConfig(
                lease_duration=120.0, renew_deadline=60.0, retry_period=30.0
            ),
            health=HealthConfig(
                window=60.0,
                min_calls=1000,  # breakers armed but not twitchy at scale
                failure_ratio=0.5,
                open_duration=30.0,
                probe_budget=1,
                aimd_qps=400.0,
            ),
        )
        with SimHarness(config=config) as harness:
            for i in range(n):
                harness.aws.add_load_balancer(f"lb{i}", NLB_REGION, nlb_hostname(i))

            def creator():
                # the fleet rolls out across the first two virtual hours
                for i in range(n):
                    harness.cluster.create(
                        "Service", fuzz._make_service(f"svc{i}", i, False)
                    )
                    yield 7200.0 / n

            harness.spawn(creator(), "creator")
            # mid-soak shard failover: kill one replica at hour 3 — the
            # survivor steals its lease, adopts ~half the keyspace, and
            # doubles its quota slice
            harness.after(
                3 * 3600.0, lambda: harness.kill_shard_replica(), "kill-replica"
            )
            harness.run_for(6 * 3600.0)
            assert harness.run_until_quiescent(6 * 3600.0, settle_window=600.0), (
                harness.stats()
            )
            violations = standard_oracles(harness)
            assert violations == [], violations[:10]
            # the convergence-SLO oracle END TO END at N=50k (ISSUE 9):
            # the fleet meets every objective ACROSS the mid-run
            # failover — journeys in flight at the kill close on the
            # survivor with their true end-to-end latency, and at this
            # scale the failover tail must fit inside the 1% budget
            slo_violations = check_slo(harness)
            assert slo_violations == [], slo_violations
            assert harness.journey.converged_total >= n
            assert harness.journey.inflight() == 0
            assert len(harness.aws.all_accelerator_arns()) == n
            ownership = harness.shard_ownership()
            assert list(ownership.values()) == [frozenset({0, 1})], ownership
            # both shards did real pre-failover work, and the soak
            # crossed the failover: >= 2 stacks were ever built
            assert harness.generations >= 2
        wall = time.monotonic() - start_wall
        assert wall < 900.0, f"50k two-shard soak took {wall:.0f}s (budget 900s)"
