"""Shared informer/lister/recorder tests: cache sync, handler
delivery, tombstones on missed deletes, resync re-delivery, factory
sharing."""

import threading
import time

import pytest

from agac_tpu.cluster import (
    EventRecorder,
    FakeCluster,
    ObjectMeta,
    Service,
    SharedInformerFactory,
    Tombstone,
)
from agac_tpu.errors import NotFoundError


def make_svc(name="web", ns="default"):
    return Service(metadata=ObjectMeta(name=name, namespace=ns))


@pytest.fixture
def cluster():
    return FakeCluster()


@pytest.fixture
def stop():
    ev = threading.Event()
    yield ev
    ev.set()


def wait_until(pred, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def test_informer_syncs_and_delivers_adds(cluster, stop):
    cluster.create("Service", make_svc("pre"))
    factory = SharedInformerFactory(cluster, resync_period=30)
    informer = factory.informer("Service")
    adds = []
    informer.add_event_handler(on_add=lambda o: adds.append(o.metadata.name))
    factory.start(stop)
    assert factory.wait_for_cache_sync(stop)
    assert wait_until(lambda: "pre" in adds)

    cluster.create("Service", make_svc("post"))
    assert wait_until(lambda: "post" in adds)


def test_informer_update_and_delete_delivery(cluster, stop):
    factory = SharedInformerFactory(cluster, resync_period=30)
    informer = factory.informer("Service")
    updates, deletes = [], []
    informer.add_event_handler(
        on_update=lambda old, new: updates.append((old.metadata.resource_version, new.metadata.resource_version)),
        on_delete=lambda o: deletes.append(o),
    )
    factory.start(stop)
    factory.wait_for_cache_sync(stop)

    cluster.create("Service", make_svc())
    obj = cluster.get("Service", "default", "web")
    obj.metadata.annotations["x"] = "y"
    cluster.update("Service", obj)
    assert wait_until(lambda: len(updates) == 1)
    old_rv, new_rv = updates[0]
    assert int(new_rv) > int(old_rv)

    cluster.delete("Service", "default", "web")
    assert wait_until(lambda: len(deletes) == 1)
    assert not isinstance(deletes[0], Tombstone)  # live delete has final state
    assert deletes[0].metadata.name == "web"


def test_lister_reads_cache(cluster, stop):
    cluster.create("Service", make_svc("a", "ns1"))
    cluster.create("Service", make_svc("b", "ns2"))
    factory = SharedInformerFactory(cluster, resync_period=30)
    informer = factory.informer("Service")
    factory.start(stop)
    factory.wait_for_cache_sync(stop)

    lister = informer.lister()
    assert lister.namespaced("ns1").get("a").metadata.name == "a"
    with pytest.raises(NotFoundError):
        lister.namespaced("ns1").get("b")
    assert len(lister.list()) == 2
    assert [o.metadata.name for o in lister.namespaced("ns2").list()] == ["b"]


def test_resync_redelivers_updates(cluster, stop):
    cluster.create("Service", make_svc())
    factory = SharedInformerFactory(cluster, resync_period=0.2)
    informer = factory.informer("Service")
    updates = []
    informer.add_event_handler(on_update=lambda old, new: updates.append(new.metadata.name))
    factory.start(stop)
    factory.wait_for_cache_sync(stop)
    # no object changes at all — resync alone must re-deliver
    assert wait_until(lambda: len(updates) >= 2, timeout=3.0)


def test_late_handler_sees_existing_cache(cluster, stop):
    cluster.create("Service", make_svc("early"))
    factory = SharedInformerFactory(cluster, resync_period=30)
    informer = factory.informer("Service")
    factory.start(stop)
    factory.wait_for_cache_sync(stop)
    adds = []
    informer.add_event_handler(on_add=lambda o: adds.append(o.metadata.name))
    assert wait_until(lambda: "early" in adds)


def test_factory_shares_informers(cluster):
    factory = SharedInformerFactory(cluster)
    assert factory.informer("Service") is factory.informer("Service")
    assert factory.informer("Service") is not factory.informer("Ingress")


def test_handler_crash_contained(cluster, stop):
    factory = SharedInformerFactory(cluster, resync_period=30)
    informer = factory.informer("Service")
    seen = []

    def bad_handler(obj):
        raise RuntimeError("handler bug")

    informer.add_event_handler(on_add=bad_handler)
    informer.add_event_handler(on_add=lambda o: seen.append(o.metadata.name))
    factory.start(stop)
    factory.wait_for_cache_sync(stop)
    cluster.create("Service", make_svc("x"))
    assert wait_until(lambda: "x" in seen)  # second handler still runs


def test_event_recorder_persists_events(cluster):
    recorder = EventRecorder(cluster, "test-controller")
    svc = cluster.create("Service", make_svc())
    recorder.eventf(svc, "Normal", "GlobalAcceleratorCreated", "Global Accelerator is created: %s", "arn:x")
    assert recorder.flush()
    events, _ = cluster.list("Event")
    assert len(events) == 1
    ev = events[0]
    assert ev.reason == "GlobalAcceleratorCreated"
    assert ev.message == "Global Accelerator is created: arn:x"
    assert ev.involved_object.kind == "Service"
    assert ev.involved_object.name == "web"
    assert ev.source.component == "test-controller"


def test_event_recorder_aggregates_repeats(cluster):
    """A repeat of the same (object, type, reason, message) within the
    aggregation window bumps count on the existing Event instead of
    creating a new object (client-go EventCorrelator behavior)."""
    recorder = EventRecorder(cluster, "test-controller")
    svc = cluster.create("Service", make_svc())
    for _ in range(5):
        recorder.event(svc, "Normal", "Repaired", "chain repaired")
    assert recorder.flush()
    events, _ = cluster.list("Event")
    assert len(events) == 1
    assert events[0].count == 5
    assert events[0].first_timestamp and events[0].last_timestamp

    # a different message is a different series
    recorder.event(svc, "Normal", "Repaired", "something else")
    assert recorder.flush()
    events, _ = cluster.list("Event")
    assert len(events) == 2


def test_event_recorder_spam_filter(cluster):
    """Distinct events on one object beyond the 25-token burst are
    dropped before reaching the apiserver."""
    recorder = EventRecorder(cluster, "test-controller", clock=lambda: 1000.0)
    svc = cluster.create("Service", make_svc())
    for i in range(40):
        recorder.event(svc, "Normal", "Flood", f"message {i}")
    assert recorder.flush()
    events, _ = cluster.list("Event")
    assert len(events) == 25

    # tokens refill with time: one more event lands 5 minutes later
    recorder._clock = lambda: 1000.0 + 301.0
    recorder.event(svc, "Normal", "Flood", "late message")
    assert recorder.flush()
    events, _ = cluster.list("Event")
    assert len(events) == 26
