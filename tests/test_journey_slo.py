"""Unit tier for the convergence SLO plane (ISSUE 9):
``agac_tpu/observability/journey.py`` (lifecycle stamps, generation
restarts, inflight/oldest views, id stability), ``slo.py`` (bucket
accounting, multi-window burn rates, shed hysteresis, violations),
and ``fleet.py`` (exposition parse/merge: counters+histograms summed,
gauges shard-labeled, failed sources named).  The live wiring is
covered by tests/test_observability.py (reconcile loop + endpoints)
and the sim/process tiers.
"""

from __future__ import annotations

import pytest

from agac_tpu.observability import fleet, journey, slo
from agac_tpu.observability.instruments import JOURNEY_BUCKETS
from agac_tpu.observability.metrics import MetricsRegistry, parse_text

GA = "global-accelerator-controller-service"
R53 = "route53-controller-service"


class FakeClock:
    def __init__(self, now: float = 0.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def make_tracker(clock=None):
    reg = MetricsRegistry()
    clock = clock or FakeClock()
    return journey.JourneyTracker(registry=reg, clock=clock), reg, clock


# ---------------------------------------------------------------------------
# journey tracker
# ---------------------------------------------------------------------------


class TestJourneyTracker:
    def test_enqueue_to_converge_observes_latency(self):
        tracker, reg, clock = make_tracker()
        tracker.observe_enqueued(GA, "ns/a", generation=1)
        clock.advance(42.0)
        assert tracker.converged(GA, "ns/a") == pytest.approx(42.0)
        samples = parse_text(reg.render())
        assert samples[
            'agac_journey_converge_seconds_count'
            f'{{controller="{GA}",trigger="spec"}}'
        ] == 1
        assert samples[
            'agac_journey_converge_seconds_sum'
            f'{{controller="{GA}",trigger="spec"}}'
        ] == pytest.approx(42.0)

    def test_reenqueue_same_generation_keeps_the_clock(self):
        tracker, _reg, clock = make_tracker()
        tracker.observe_enqueued(GA, "ns/a", generation=1)
        clock.advance(10.0)
        tracker.observe_enqueued(GA, "ns/a", generation=1)
        clock.advance(5.0)
        assert tracker.converged(GA, "ns/a") == pytest.approx(15.0)

    def test_newer_generation_restarts_the_clock(self):
        # the user experiences latency to the edit they LAST wrote
        tracker, _reg, clock = make_tracker()
        tracker.observe_enqueued(GA, "ns/a", generation=1)
        clock.advance(100.0)
        tracker.observe_enqueued(GA, "ns/a", generation=2)
        clock.advance(3.0)
        assert tracker.converged(GA, "ns/a") == pytest.approx(3.0)

    def test_close_of_unknown_key_is_a_noop(self):
        tracker, _reg, _clock = make_tracker()
        assert tracker.converged(GA, "ns/ghost") is None
        assert tracker.deleted(GA, "ns/ghost") is None

    def test_stage_counters_and_attempt_counts(self):
        tracker, reg, _clock = make_tracker()
        tracker.observe_enqueued(GA, "ns/a")
        tracker.attempt(GA, "ns/a")
        tracker.stage(GA, "ns/a", journey.STAGE_REQUEUED)
        tracker.attempt(GA, "ns/a")
        tracker.stage(GA, "ns/a", journey.STAGE_PARKED)
        samples = parse_text(reg.render())
        prefix = f'agac_journey_stages_total{{controller="{GA}",stage='
        assert samples[prefix + '"enqueued"}'] == 1
        assert samples[prefix + '"attempt"}'] == 2
        assert samples[prefix + '"requeued"}'] == 1
        assert samples[prefix + '"parked"}'] == 1

    def test_inflight_and_oldest_age_views(self):
        tracker, reg, clock = make_tracker()
        tracker.observe_enqueued(GA, "ns/old")
        clock.advance(30.0)
        tracker.observe_enqueued(GA, "ns/new")
        tracker.observe_enqueued(R53, "ns/other")
        assert tracker.inflight() == 3
        assert tracker.inflight(GA) == 2
        assert tracker.oldest_age(GA) == pytest.approx(30.0)
        samples = parse_text(reg.render())
        assert samples[f'agac_journey_inflight{{controller="{GA}"}}'] == 2
        assert samples[
            f'agac_journey_oldest_unconverged_age_seconds{{controller="{GA}"}}'
        ] == pytest.approx(30.0)
        tracker.converged(GA, "ns/old")
        assert tracker.oldest_age(GA) == pytest.approx(0.0)

    def test_slowest_lists_oldest_first_with_ids(self):
        tracker, _reg, clock = make_tracker()
        tracker.observe_enqueued(GA, "ns/first", generation=3)
        clock.advance(5.0)
        tracker.observe_enqueued(GA, "ns/second")
        slowest = tracker.slowest()
        assert [j["key"] for j in slowest] == ["ns/first", "ns/second"]
        assert slowest[0]["id"] == "ns/first@g3#1"
        assert slowest[0]["id"] == tracker.journey_id(GA, "ns/first")

    def test_drop_closes_without_observing_latency(self):
        tracker, reg, clock = make_tracker()
        tracker.observe_enqueued(GA, "ns/a")
        clock.advance(1000.0)
        tracker.drop(GA, "ns/a")
        assert tracker.inflight() == 0
        samples = parse_text(reg.render())
        # nothing observed into the histogram — a dropped item is not
        # a convergence
        assert not any(
            name.startswith("agac_journey_converge_seconds_count")
            and value > 0
            for name, value in samples.items()
        )

    def test_handoff_trigger_labels_the_histogram(self):
        tracker, reg, clock = make_tracker()
        tracker.observe_enqueued(
            GA, "ns/adopted", trigger=journey.TRIGGER_HANDOFF
        )
        clock.advance(2.0)
        tracker.converged(GA, "ns/adopted")
        samples = parse_text(reg.render())
        assert samples[
            'agac_journey_converge_seconds_count'
            f'{{controller="{GA}",trigger="handoff"}}'
        ] == 1

    def test_inflight_cap_drops_new_opens(self):
        reg = MetricsRegistry()
        tracker = journey.JourneyTracker(
            registry=reg, clock=FakeClock(), max_inflight=2
        )
        tracker.observe_enqueued(GA, "ns/a")
        tracker.observe_enqueued(GA, "ns/b")
        tracker.observe_enqueued(GA, "ns/c")
        assert tracker.inflight() == 2
        assert tracker.dropped_total == 1


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


def make_engine(**kwargs):
    clock = FakeClock()
    reg = MetricsRegistry()
    tracker = journey.JourneyTracker(registry=reg, clock=clock)
    engine = slo.SLOEngine(
        registry=reg, clock=clock, journey_tracker=tracker, **kwargs
    )
    return engine, tracker, clock, reg


def converge_after(tracker, clock, key, seconds, controller=GA):
    tracker.observe_enqueued(controller, key)
    clock.advance(seconds)
    tracker.converged(controller, key)


class TestSLOEngine:
    def test_threshold_must_sit_on_a_bucket_bound(self):
        with pytest.raises(ValueError):
            slo.SLOObjective("bad", 77.0, slo.GA_CONTROLLERS)
        # every shipped objective aligns by construction
        for objective in slo.default_objectives():
            assert objective.threshold_seconds in JOURNEY_BUCKETS

    def test_violations_on_cumulative_good_fraction(self):
        engine, tracker, clock, _reg = make_engine()
        converge_after(tracker, clock, "ns/fast", 5.0)
        assert engine.violations() == []
        converge_after(tracker, clock, "ns/slow", 500.0)
        violations = engine.violations()
        assert len(violations) == 1 and "ga_converge_p99" in violations[0]

    def test_burn_rate_rises_and_decays_with_the_window(self):
        engine, tracker, clock, _reg = make_engine(windows=(100.0, 1000.0))
        # a healthy baseline so the long window has history
        for i in range(50):
            converge_after(tracker, clock, f"ns/ok{i}", 1.0)
            clock.advance(10.0)
            engine.tick()
        # a burst of slow closures inside the short window
        for i in range(5):
            converge_after(tracker, clock, f"ns/slow{i}", 200.0)
        burn = engine.tick()
        short = burn["ga_converge_p99"][100.0]
        assert short > 1.0  # 5 bad out of ~7 in-window >> the 1% budget
        # let the burst age out of the short window: burn decays to 0
        for i in range(30):
            clock.advance(10.0)
            burn = engine.tick()
        assert burn["ga_converge_p99"][100.0] == 0.0

    def test_shedding_trips_on_both_windows_and_clears_with_hysteresis(self):
        engine, tracker, clock, _reg = make_engine(windows=(100.0, 400.0))
        engine.tick()
        # sustained badness: every closure blows the threshold
        for i in range(12):
            converge_after(tracker, clock, f"ns/slow{i}", 150.0)
            clock.advance(30.0)
            engine.tick()
        assert engine.shedding
        assert engine.shed_activations == 1
        assert engine.should_shed("gc-sweep") is True
        # recovery: good closures age the badness out of the short
        # window; hysteresis clears at < shed_burn/2
        for i in range(30):
            converge_after(tracker, clock, f"ns/ok{i}", 1.0)
            clock.advance(30.0)
            engine.tick()
        assert not engine.shedding
        assert engine.should_shed("gc-sweep") is False

    def test_shed_gates_off_observes_without_deferring(self):
        engine, tracker, clock, _reg = make_engine(
            windows=(100.0, 400.0), shed_gates=False
        )
        engine.tick()
        for i in range(12):
            converge_after(tracker, clock, f"ns/slow{i}", 150.0)
            clock.advance(30.0)
            engine.tick()
        assert engine.shedding  # the state machine still runs
        assert engine.shed_activations == 1
        assert engine.should_shed("gc-sweep") is False  # but never defers

    def test_global_gate_is_a_noop_without_an_engine(self):
        previous = slo.install_engine(None)
        try:
            assert slo.should_shed("gc-sweep") is False
            assert slo.status_or_disabled() == {"enabled": False}
        finally:
            slo.install_engine(previous)

    def test_status_carries_objectives_and_slowest_journeys(self):
        engine, tracker, clock, _reg = make_engine()
        converge_after(tracker, clock, "ns/done", 5.0)
        tracker.observe_enqueued(GA, "ns/stuck")
        clock.advance(50.0)
        engine.tick()
        status = engine.status()
        assert status["enabled"] is True
        by_name = {o["name"]: o for o in status["objectives"]}
        assert by_name["ga_converge_p99"]["journeys"] == 1
        assert by_name["ga_converge_p99"]["healthy"] is True
        # no record journeys yet: vacuously healthy, no data
        assert by_name["record_converge_p99"]["journeys"] == 0
        assert status["slowest_unconverged"][0]["key"] == "ns/stuck"
        assert status["journeys"]["inflight"] == 1

    def test_metrics_exported_on_tick(self):
        engine, tracker, clock, reg = make_engine()
        converge_after(tracker, clock, "ns/a", 5.0)
        engine.tick()
        samples = parse_text(reg.render())
        assert samples['agac_slo_healthy{objective="ga_converge_p99"}'] == 1
        assert 'agac_slo_burn_rate{objective="ga_converge_p99",window="300s"}' in samples
        assert samples["agac_slo_shedding"] == 0
        assert samples["agac_slo_evaluations_total"] == 1

    def test_estimate_quantile_interpolates(self):
        buckets = [(1.0, 10.0), (2.0, 20.0)]
        assert slo.estimate_quantile(buckets, 20.0, 0.5) == pytest.approx(1.0)
        assert slo.estimate_quantile(buckets, 20.0, 0.75) == pytest.approx(1.5)
        assert slo.estimate_quantile([], 0.0, 0.99) == 0.0


# ---------------------------------------------------------------------------
# autoscaler accessors (ISSUE 13) — the stable in-process reads the
# signal collector consumes
# ---------------------------------------------------------------------------


class TestAutoscalerAccessors:
    def test_burn_snapshot_empty_before_first_tick(self):
        engine, _tracker, _clock, _reg = make_engine()
        assert engine.burn_snapshot() == {}

    def test_burn_snapshot_mirrors_the_last_tick(self):
        engine, tracker, clock, _reg = make_engine(windows=(100.0, 1000.0))
        engine.tick()  # baseline sample for the window deltas
        converge_after(tracker, clock, "ns/slow", 200.0)
        ticked = engine.tick()
        snapshot = engine.burn_snapshot()
        assert snapshot == ticked
        # keyed by objective name then RAW float window
        assert set(snapshot["ga_converge_p99"]) == {100.0, 1000.0}
        assert snapshot["ga_converge_p99"][100.0] > 1.0

    def test_burn_snapshot_is_a_copy(self):
        engine, tracker, clock, _reg = make_engine(windows=(100.0, 1000.0))
        converge_after(tracker, clock, "ns/a", 1.0)
        engine.tick()
        engine.burn_snapshot()["ga_converge_p99"][100.0] = 999.0
        assert engine.burn_snapshot()["ga_converge_p99"][100.0] != 999.0

    def test_oldest_unconverged_age_matches_oldest_age(self):
        tracker, _reg, clock = make_tracker()
        assert tracker.oldest_unconverged_age() == 0.0
        tracker.observe_enqueued(GA, "ns/old")
        clock.advance(45.0)
        tracker.observe_enqueued(R53, "ns/young")
        assert tracker.oldest_unconverged_age() == pytest.approx(45.0)
        assert tracker.oldest_unconverged_age(GA) == pytest.approx(45.0)
        assert tracker.oldest_unconverged_age(R53) == pytest.approx(0.0)
        tracker.converged(GA, "ns/old")
        assert tracker.oldest_unconverged_age() == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# fleet merge
# ---------------------------------------------------------------------------


def render_replica(converge_count: int, keys_owned: int) -> str:
    reg = MetricsRegistry()
    clock = FakeClock()
    tracker = journey.JourneyTracker(registry=reg, clock=clock)
    for i in range(converge_count):
        converge_after(tracker, clock, f"ns/k{i}", 5.0)
    reg.gauge("agac_shard_keys_owned", "keys").set(keys_owned)
    reg.counter("agac_gc_sweeps_total", "sweeps").inc(3)
    return reg.render()


class TestFleetMerge:
    def test_counters_and_histograms_sum_gauges_get_shard_labels(self):
        merged, notes = fleet.merge_expositions(
            {"r1": render_replica(2, 7), "r2": render_replica(3, 5)}
        )
        assert notes == []
        text = fleet.render_families(merged)
        samples = parse_text(text)
        # histogram totals SUM across replicas
        assert samples[
            'agac_journey_converge_seconds_count'
            f'{{controller="{GA}",trigger="spec"}}'
        ] == 5
        # counters sum (3 sweeps on each replica)
        assert samples["agac_gc_sweeps_total"] == 6

    def test_gauges_labeled_by_shard_never_summed(self):
        merged, _ = fleet.merge_expositions(
            {"r1": render_replica(0, 7), "r2": render_replica(0, 5)}
        )
        samples = merged["agac_shard_keys_owned"].samples
        assert samples['agac_shard_keys_owned{shard="r1"}'] == 7
        assert samples['agac_shard_keys_owned{shard="r2"}'] == 5
        assert "agac_shard_keys_owned" not in samples  # no unlabeled sum

    def test_failed_source_is_named_not_silent(self):
        def boom():
            raise OSError("connection refused")

        view = fleet.FleetView(
            {"alive": lambda: render_replica(1, 1), "dead": boom}
        )
        text = view.render()
        assert "# fleet-source-failed: dead" in text
        assert "# fleet-sources: alive" in text
        samples = parse_text(text)
        assert samples[
            'agac_journey_converge_seconds_count'
            f'{{controller="{GA}",trigger="spec"}}'
        ] == 1

    def test_converge_percentiles_from_merged_view(self):
        merged, _ = fleet.merge_expositions(
            {"r1": render_replica(4, 0), "r2": render_replica(4, 0)}
        )
        pcts = fleet.converge_percentiles(merged)
        assert pcts["ga"]["count"] == 8
        assert 0 < pcts["ga"]["p99_s"] <= 10.0
        assert pcts["record"]["count"] == 0
