#!/bin/sh
# Real-apiserver e2e driver — the analog of the reference's
# hack/kind-with-registry.sh + .github/workflows/e2e.yml flow, adapted
# to a controller that runs on the HOST (no image build needed for the
# protocol tier): create a kind cluster, generate webhook TLS material
# for an apiserver-reachable host address, and run the env-gated
# pytest tier (tests/test_kind_e2e.py) against it.
#
# Usage:
#   K8S_VERSION=1.31.0 ./hack/kind-e2e.sh            # create, test, delete
#   KEEP_CLUSTER=1 ./hack/kind-e2e.sh                # leave cluster running
#   E2E_KIND_SOAK=1 ./hack/kind-e2e.sh               # include apiserver-restart soak
#   HELM_STAGE=1 ./hack/kind-e2e.sh                  # also build image + helm install
#   DRY_RUN=1 ./hack/kind-e2e.sh                     # print every command, execute none
#
# Requirements: kind, kubectl, docker, openssl, python (repo deps);
# helm additionally when HELM_STAGE=1.  The preflight below fails
# fast with the FULL list of whatever is missing.  DRY_RUN=1 needs
# none of them: it prints the exact command flow (with placeholder
# values where a live cluster would be probed) so the script's logic
# can be audited — and is unit-tested on every `make test` — without
# docker (tests/test_kind_script.py).
set -o errexit

K8S_VERSION="${K8S_VERSION:-1.31.0}"
CLUSTER_NAME="${CLUSTER_NAME:-agac-e2e}"
WEBHOOK_PORT="${WEBHOOK_PORT:-18443}"
DRY_RUN="${DRY_RUN:-0}"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"

# --- preflight -----------------------------------------------------------
# collect EVERY missing tool before failing, so one run reports the
# complete shopping list instead of dying on the first gap
required="kind kubectl docker openssl python"
if [ "${HELM_STAGE:-0}" = "1" ]; then
  required="${required} helm"
fi
missing=""
for tool in ${required}; do
  command -v "${tool}" >/dev/null 2>&1 || missing="${missing} ${tool}"
done
if [ -n "${missing}" ]; then
  if [ "${DRY_RUN}" = "1" ]; then
    echo "preflight (dry-run, continuing): missing binaries:${missing}" >&2
  else
    echo "kind-e2e preflight: missing required binaries:${missing}" >&2
    echo "install them (see the header of hack/kind-e2e.sh), then re-run" >&2
    exit 3
  fi
fi

# every effectful command goes through run(): always echoed (a trace
# for CI logs), executed unless DRY_RUN=1
run() {
  printf '+ %s\n' "$*"
  if [ "${DRY_RUN}" = "1" ]; then
    return 0
  fi
  "$@"
}

BANNER_SUFFIX=""
if [ "${DRY_RUN}" = "1" ]; then
  BANNER_SUFFIX=" [dry-run: nothing executed]"
fi

WORKDIR="$(mktemp -d)"

cleanup() {
  if [ "${KEEP_CLUSTER:-0}" != "1" ]; then
    run kind delete cluster --name "${CLUSTER_NAME}" || true
  fi
  rm -rf "${WORKDIR}"
}
trap cleanup EXIT

# --- cluster -------------------------------------------------------------
run kind create cluster --name "${CLUSTER_NAME}" \
  --image "kindest/node:v${K8S_VERSION}" --wait 120s
run kubectl cluster-info --context "kind-${CLUSTER_NAME}"

# --- webhook TLS material ------------------------------------------------
# The webhook runs on the host; the apiserver (inside the kind node
# container) reaches it via the docker network gateway.  Issue a cert
# for that IP with a throwaway CA whose bundle goes into the
# ValidatingWebhookConfiguration.
printf '+ %s\n' "docker network inspect kind -f '{{(index .IPAM.Config 0).Gateway}}'"
if [ "${DRY_RUN}" = "1" ]; then
  HOST_IP="<docker-network-gateway>"
else
  HOST_IP="$(docker network inspect kind -f '{{(index .IPAM.Config 0).Gateway}}')"
  if [ -z "${HOST_IP}" ]; then
    echo "could not determine docker network gateway for 'kind'" >&2
    exit 1
  fi
fi
run openssl req -x509 -newkey rsa:2048 -nodes -days 2 \
  -keyout "${WORKDIR}/ca.key" -out "${WORKDIR}/ca.crt" \
  -subj "/CN=agac-e2e-ca"
run openssl req -newkey rsa:2048 -nodes \
  -keyout "${WORKDIR}/webhook.key" -out "${WORKDIR}/webhook.csr" \
  -subj "/CN=agac-e2e-webhook"
cat > "${WORKDIR}/san.cnf" <<EOF
subjectAltName=IP:${HOST_IP}
EOF
run openssl x509 -req -in "${WORKDIR}/webhook.csr" \
  -CA "${WORKDIR}/ca.crt" -CAkey "${WORKDIR}/ca.key" -CAcreateserial \
  -days 2 -extfile "${WORKDIR}/san.cnf" \
  -out "${WORKDIR}/webhook.crt"

printf '+ %s\n' "base64 < ${WORKDIR}/ca.crt | tr -d '\\n'"
if [ "${DRY_RUN}" = "1" ]; then
  E2E_WEBHOOK_CA_BUNDLE="<ca-bundle-base64>"
else
  E2E_WEBHOOK_CA_BUNDLE="$(base64 < "${WORKDIR}/ca.crt" | tr -d '\n')"
fi

# --- protocol tier -------------------------------------------------------
KUBECONFIG_FILE="${WORKDIR}/kubeconfig"
printf '+ %s\n' "kind get kubeconfig --name ${CLUSTER_NAME} > ${KUBECONFIG_FILE}"
if [ "${DRY_RUN}" != "1" ]; then
  kind get kubeconfig --name "${CLUSTER_NAME}" > "${KUBECONFIG_FILE}"
fi

cd "${REPO_ROOT}"
# E2E_KIND_SOAK is forwarded EXPLICITLY (it would propagate through
# the environment anyway) so the DRY_RUN audit and its unit tier
# render the soak leg's plumbing instead of relying on inheritance
run env \
  E2E_KIND=1 \
  E2E_KIND_SOAK="${E2E_KIND_SOAK:-0}" \
  KUBECONFIG="${KUBECONFIG_FILE}" \
  E2E_WEBHOOK_URL="https://${HOST_IP}:${WEBHOOK_PORT}" \
  E2E_WEBHOOK_CERT="${WORKDIR}/webhook.crt" \
  E2E_WEBHOOK_KEY="${WORKDIR}/webhook.key" \
  E2E_WEBHOOK_CA_BUNDLE="${E2E_WEBHOOK_CA_BUNDLE}" \
  E2E_KIND_NODE="${CLUSTER_NAME}-control-plane" \
  python -m pytest tests/test_kind_e2e.py -v

# --- optional: image + helm chart deploy proof (VERDICT r2 next#4) -------
# Installs the chart with BOTH processes enabled (controller on the
# fake cloud, webhook with script-generated certs — no cert-manager
# needed), then asserts the deployment actually works: a reconcile
# Event through the chart's controller, and the admission denial
# through the chart's webhook Service.
if [ "${HELM_STAGE:-0}" = "1" ]; then
  IMAGE="aws-global-accelerator-controller:e2e"
  run docker build -t "${IMAGE}" "${REPO_ROOT}"
  run kind load docker-image "${IMAGE}" --name "${CLUSTER_NAME}"

  KC="kubectl --kubeconfig ${KUBECONFIG_FILE}"

  # serving cert for the in-cluster webhook Service DNS name, signed
  # by the same throwaway CA as the host-webhook cert above
  WEBHOOK_SVC="aws-global-accelerator-controller-webhook"
  run openssl req -newkey rsa:2048 -nodes \
    -keyout "${WORKDIR}/chart-webhook.key" -out "${WORKDIR}/chart-webhook.csr" \
    -subj "/CN=${WEBHOOK_SVC}.default.svc"
  cat > "${WORKDIR}/chart-san.cnf" <<EOF
subjectAltName=DNS:${WEBHOOK_SVC}.default.svc,DNS:${WEBHOOK_SVC}.default.svc.cluster.local
EOF
  run openssl x509 -req -in "${WORKDIR}/chart-webhook.csr" \
    -CA "${WORKDIR}/ca.crt" -CAkey "${WORKDIR}/ca.key" -CAcreateserial \
    -days 2 -extfile "${WORKDIR}/chart-san.cnf" \
    -out "${WORKDIR}/chart-webhook.crt"
  run ${KC} create secret tls agac-e2e-webhook-cert \
    --cert "${WORKDIR}/chart-webhook.crt" --key "${WORKDIR}/chart-webhook.key"

  # LB name/hostname pair from tests/fixtures.py, so the fake cloud
  # recognizes the hostname we patch into the sample Service's status
  NLB_HOSTNAME="testlb-0123456789abcdef.elb.us-west-2.amazonaws.com"
  run helm install agac "${REPO_ROOT}/charts/aws-global-accelerator-controller" \
    --kubeconfig "${KUBECONFIG_FILE}" \
    --set image.repository=aws-global-accelerator-controller \
    --set image.tag=e2e \
    --set image.pullPolicy=Never \
    --set webhook.enabled=true \
    --set webhook.certManager.enabled=false \
    --set webhook.existingCertSecret=agac-e2e-webhook-cert \
    --set webhook.caBundle="${E2E_WEBHOOK_CA_BUNDLE}" \
    --set env.AGAC_CLOUD=fake \
    --set env.AGAC_FAKE_LBS="testlb=${NLB_HOSTNAME}" \
    --set env.AGAC_FAKE_ZONES="example.com."
  run ${KC} rollout status deployment/aws-global-accelerator-controller --timeout=180s
  run ${KC} rollout status deployment/${WEBHOOK_SVC} --timeout=180s

  # reconcile proof: give the sample Service an LB hostname through
  # the status subresource (kind has no cloud LB controller — we play
  # aws-load-balancer-controller, same trick as test_kind_e2e.py) and
  # wait for the chart-deployed controller's Event
  run ${KC} apply -f "${REPO_ROOT}/config/samples/nlb-public-service.yaml"
  run ${KC} patch service sample-nlb --subresource=status --type=merge \
    -p "{\"status\":{\"loadBalancer\":{\"ingress\":[{\"hostname\":\"${NLB_HOSTNAME}\"}]}}}"
  printf '+ %s\n' "poll: ${KC} get events --field-selector reason=GlobalAcceleratorCreated,involvedObject.name=sample-nlb -o name (120s budget)"
  if [ "${DRY_RUN}" != "1" ]; then
    i=0
    until ${KC} get events \
        --field-selector reason=GlobalAcceleratorCreated,involvedObject.name=sample-nlb \
        -o name 2>/dev/null | grep -q .; do
      i=$((i+1))
      if [ "$i" -gt 60 ]; then
        echo "HELM_STAGE: no GlobalAcceleratorCreated Event after 120s" >&2
        ${KC} logs deployment/aws-global-accelerator-controller --tail=100 >&2 || true
        exit 1
      fi
      sleep 2
    done
  fi

  # admission proof: the chart's ValidatingWebhookConfiguration +
  # webhook Service must allow a weight change and deny an ARN change
  # with the reference's exact message (e2e/e2e_test.go:78-98)
  run ${KC} apply -f "${REPO_ROOT}/config/samples/endpointgroupbinding.yaml"
  run ${KC} patch endpointgroupbinding sample-binding --type=merge \
    -p '{"spec":{"weight":64}}'
  printf '+ %s\n' "expect-denial: ${KC} patch endpointgroupbinding sample-binding --type=merge -p '{\"spec\":{\"endpointGroupArn\":\"arn:aws:globalaccelerator::123456789012:accelerator/changed\"}}' (stderr must contain 'immutable')"
  if [ "${DRY_RUN}" != "1" ]; then
    if ${KC} patch endpointgroupbinding sample-binding --type=merge \
        -p '{"spec":{"endpointGroupArn":"arn:aws:globalaccelerator::123456789012:accelerator/changed"}}' \
        2> "${WORKDIR}/deny.err"; then
      echo "HELM_STAGE: ARN mutation was NOT denied by the chart webhook" >&2
      exit 1
    fi
    grep -q "immutable" "${WORKDIR}/deny.err" || {
      echo "HELM_STAGE: denial lacked the immutability message:" >&2
      cat "${WORKDIR}/deny.err" >&2
      exit 1
    }
  fi

  # leader election through the chart's RBAC
  run ${KC} get lease aws-global-accelerator-controller -o yaml
  echo "HELM_STAGE PASSED (reconcile Event + webhook denial through the chart)${BANNER_SUFFIX}"
fi

echo "kind e2e tier PASSED (k8s ${K8S_VERSION})${BANNER_SUFFIX}"
