#!/bin/sh
# Real-apiserver e2e driver — the analog of the reference's
# hack/kind-with-registry.sh + .github/workflows/e2e.yml flow, adapted
# to a controller that runs on the HOST (no image build needed for the
# protocol tier): create a kind cluster, generate webhook TLS material
# for an apiserver-reachable host address, and run the env-gated
# pytest tier (tests/test_kind_e2e.py) against it.
#
# Usage:
#   K8S_VERSION=1.31.0 ./hack/kind-e2e.sh            # create, test, delete
#   KEEP_CLUSTER=1 ./hack/kind-e2e.sh                # leave cluster running
#   E2E_KIND_SOAK=1 ./hack/kind-e2e.sh               # include apiserver-restart soak
#   HELM_STAGE=1 ./hack/kind-e2e.sh                  # also build image + helm install
#
# Requirements: kind, kubectl, docker, openssl, python (repo deps).
set -o errexit

K8S_VERSION="${K8S_VERSION:-1.31.0}"
CLUSTER_NAME="${CLUSTER_NAME:-agac-e2e}"
WEBHOOK_PORT="${WEBHOOK_PORT:-18443}"
WORKDIR="$(mktemp -d)"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"

cleanup() {
  if [ "${KEEP_CLUSTER:-0}" != "1" ]; then
    kind delete cluster --name "${CLUSTER_NAME}" || true
  fi
  rm -rf "${WORKDIR}"
}
trap cleanup EXIT

# --- cluster -------------------------------------------------------------
kind create cluster --name "${CLUSTER_NAME}" \
  --image "kindest/node:v${K8S_VERSION}" --wait 120s
kubectl cluster-info --context "kind-${CLUSTER_NAME}"

# --- webhook TLS material ------------------------------------------------
# The webhook runs on the host; the apiserver (inside the kind node
# container) reaches it via the docker network gateway.  Issue a cert
# for that IP with a throwaway CA whose bundle goes into the
# ValidatingWebhookConfiguration.
HOST_IP="$(docker network inspect kind -f '{{(index .IPAM.Config 0).Gateway}}')"
if [ -z "${HOST_IP}" ]; then
  echo "could not determine docker network gateway for 'kind'" >&2
  exit 1
fi
openssl req -x509 -newkey rsa:2048 -nodes -days 2 \
  -keyout "${WORKDIR}/ca.key" -out "${WORKDIR}/ca.crt" \
  -subj "/CN=agac-e2e-ca" >/dev/null 2>&1
openssl req -newkey rsa:2048 -nodes \
  -keyout "${WORKDIR}/webhook.key" -out "${WORKDIR}/webhook.csr" \
  -subj "/CN=agac-e2e-webhook" >/dev/null 2>&1
cat > "${WORKDIR}/san.cnf" <<EOF
subjectAltName=IP:${HOST_IP}
EOF
openssl x509 -req -in "${WORKDIR}/webhook.csr" \
  -CA "${WORKDIR}/ca.crt" -CAkey "${WORKDIR}/ca.key" -CAcreateserial \
  -days 2 -extfile "${WORKDIR}/san.cnf" \
  -out "${WORKDIR}/webhook.crt" >/dev/null 2>&1

E2E_WEBHOOK_CA_BUNDLE="$(base64 < "${WORKDIR}/ca.crt" | tr -d '\n')"

# --- protocol tier -------------------------------------------------------
KUBECONFIG_FILE="${WORKDIR}/kubeconfig"
kind get kubeconfig --name "${CLUSTER_NAME}" > "${KUBECONFIG_FILE}"

cd "${REPO_ROOT}"
E2E_KIND=1 \
KUBECONFIG="${KUBECONFIG_FILE}" \
E2E_WEBHOOK_URL="https://${HOST_IP}:${WEBHOOK_PORT}" \
E2E_WEBHOOK_CERT="${WORKDIR}/webhook.crt" \
E2E_WEBHOOK_KEY="${WORKDIR}/webhook.key" \
E2E_WEBHOOK_CA_BUNDLE="${E2E_WEBHOOK_CA_BUNDLE}" \
E2E_KIND_NODE="${CLUSTER_NAME}-control-plane" \
python -m pytest tests/test_kind_e2e.py -v

# --- optional: image + helm chart deploy proof (VERDICT r2 next#4) -------
# Installs the chart with BOTH processes enabled (controller on the
# fake cloud, webhook with script-generated certs — no cert-manager
# needed), then asserts the deployment actually works: a reconcile
# Event through the chart's controller, and the admission denial
# through the chart's webhook Service.
if [ "${HELM_STAGE:-0}" = "1" ]; then
  IMAGE="aws-global-accelerator-controller:e2e"
  docker build -t "${IMAGE}" "${REPO_ROOT}"
  kind load docker-image "${IMAGE}" --name "${CLUSTER_NAME}"

  KC="kubectl --kubeconfig ${KUBECONFIG_FILE}"

  # serving cert for the in-cluster webhook Service DNS name, signed
  # by the same throwaway CA as the host-webhook cert above
  WEBHOOK_SVC="aws-global-accelerator-controller-webhook"
  openssl req -newkey rsa:2048 -nodes \
    -keyout "${WORKDIR}/chart-webhook.key" -out "${WORKDIR}/chart-webhook.csr" \
    -subj "/CN=${WEBHOOK_SVC}.default.svc" >/dev/null 2>&1
  cat > "${WORKDIR}/chart-san.cnf" <<EOF
subjectAltName=DNS:${WEBHOOK_SVC}.default.svc,DNS:${WEBHOOK_SVC}.default.svc.cluster.local
EOF
  openssl x509 -req -in "${WORKDIR}/chart-webhook.csr" \
    -CA "${WORKDIR}/ca.crt" -CAkey "${WORKDIR}/ca.key" -CAcreateserial \
    -days 2 -extfile "${WORKDIR}/chart-san.cnf" \
    -out "${WORKDIR}/chart-webhook.crt" >/dev/null 2>&1
  ${KC} create secret tls agac-e2e-webhook-cert \
    --cert "${WORKDIR}/chart-webhook.crt" --key "${WORKDIR}/chart-webhook.key"

  # LB name/hostname pair from tests/fixtures.py, so the fake cloud
  # recognizes the hostname we patch into the sample Service's status
  NLB_HOSTNAME="testlb-0123456789abcdef.elb.us-west-2.amazonaws.com"
  helm install agac "${REPO_ROOT}/charts/aws-global-accelerator-controller" \
    --kubeconfig "${KUBECONFIG_FILE}" \
    --set image.repository=aws-global-accelerator-controller \
    --set image.tag=e2e \
    --set image.pullPolicy=Never \
    --set webhook.enabled=true \
    --set webhook.certManager.enabled=false \
    --set webhook.existingCertSecret=agac-e2e-webhook-cert \
    --set webhook.caBundle="${E2E_WEBHOOK_CA_BUNDLE}" \
    --set env.AGAC_CLOUD=fake \
    --set env.AGAC_FAKE_LBS="testlb=${NLB_HOSTNAME}" \
    --set env.AGAC_FAKE_ZONES="example.com."
  ${KC} rollout status deployment/aws-global-accelerator-controller --timeout=180s
  ${KC} rollout status deployment/${WEBHOOK_SVC} --timeout=180s

  # reconcile proof: give the sample Service an LB hostname through
  # the status subresource (kind has no cloud LB controller — we play
  # aws-load-balancer-controller, same trick as test_kind_e2e.py) and
  # wait for the chart-deployed controller's Event
  ${KC} apply -f "${REPO_ROOT}/config/samples/nlb-public-service.yaml"
  ${KC} patch service sample-nlb --subresource=status --type=merge \
    -p "{\"status\":{\"loadBalancer\":{\"ingress\":[{\"hostname\":\"${NLB_HOSTNAME}\"}]}}}"
  i=0
  until ${KC} get events \
      --field-selector reason=GlobalAcceleratorCreated,involvedObject.name=sample-nlb \
      -o name 2>/dev/null | grep -q .; do
    i=$((i+1))
    if [ "$i" -gt 60 ]; then
      echo "HELM_STAGE: no GlobalAcceleratorCreated Event after 120s" >&2
      ${KC} logs deployment/aws-global-accelerator-controller --tail=100 >&2 || true
      exit 1
    fi
    sleep 2
  done

  # admission proof: the chart's ValidatingWebhookConfiguration +
  # webhook Service must allow a weight change and deny an ARN change
  # with the reference's exact message (e2e/e2e_test.go:78-98)
  ${KC} apply -f "${REPO_ROOT}/config/samples/endpointgroupbinding.yaml"
  ${KC} patch endpointgroupbinding sample-binding --type=merge \
    -p '{"spec":{"weight":64}}'
  if ${KC} patch endpointgroupbinding sample-binding --type=merge \
      -p '{"spec":{"endpointGroupArn":"arn:aws:globalaccelerator::123456789012:accelerator/changed"}}' \
      2> "${WORKDIR}/deny.err"; then
    echo "HELM_STAGE: ARN mutation was NOT denied by the chart webhook" >&2
    exit 1
  fi
  grep -q "immutable" "${WORKDIR}/deny.err" || {
    echo "HELM_STAGE: denial lacked the immutability message:" >&2
    cat "${WORKDIR}/deny.err" >&2
    exit 1
  }

  # leader election through the chart's RBAC
  ${KC} get lease aws-global-accelerator-controller -o yaml
  echo "HELM_STAGE PASSED (reconcile Event + webhook denial through the chart)"
fi

echo "kind e2e tier PASSED (k8s ${K8S_VERSION})"
