#!/bin/sh
# Real-apiserver e2e driver — the analog of the reference's
# hack/kind-with-registry.sh + .github/workflows/e2e.yml flow, adapted
# to a controller that runs on the HOST (no image build needed for the
# protocol tier): create a kind cluster, generate webhook TLS material
# for an apiserver-reachable host address, and run the env-gated
# pytest tier (tests/test_kind_e2e.py) against it.
#
# Usage:
#   K8S_VERSION=1.31.0 ./hack/kind-e2e.sh            # create, test, delete
#   KEEP_CLUSTER=1 ./hack/kind-e2e.sh                # leave cluster running
#   E2E_KIND_SOAK=1 ./hack/kind-e2e.sh               # include apiserver-restart soak
#   HELM_STAGE=1 ./hack/kind-e2e.sh                  # also build image + helm install
#
# Requirements: kind, kubectl, docker, openssl, python (repo deps).
set -o errexit

K8S_VERSION="${K8S_VERSION:-1.31.0}"
CLUSTER_NAME="${CLUSTER_NAME:-agac-e2e}"
WEBHOOK_PORT="${WEBHOOK_PORT:-18443}"
WORKDIR="$(mktemp -d)"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"

cleanup() {
  if [ "${KEEP_CLUSTER:-0}" != "1" ]; then
    kind delete cluster --name "${CLUSTER_NAME}" || true
  fi
  rm -rf "${WORKDIR}"
}
trap cleanup EXIT

# --- cluster -------------------------------------------------------------
kind create cluster --name "${CLUSTER_NAME}" \
  --image "kindest/node:v${K8S_VERSION}" --wait 120s
kubectl cluster-info --context "kind-${CLUSTER_NAME}"

# --- webhook TLS material ------------------------------------------------
# The webhook runs on the host; the apiserver (inside the kind node
# container) reaches it via the docker network gateway.  Issue a cert
# for that IP with a throwaway CA whose bundle goes into the
# ValidatingWebhookConfiguration.
HOST_IP="$(docker network inspect kind -f '{{(index .IPAM.Config 0).Gateway}}')"
if [ -z "${HOST_IP}" ]; then
  echo "could not determine docker network gateway for 'kind'" >&2
  exit 1
fi
openssl req -x509 -newkey rsa:2048 -nodes -days 2 \
  -keyout "${WORKDIR}/ca.key" -out "${WORKDIR}/ca.crt" \
  -subj "/CN=agac-e2e-ca" >/dev/null 2>&1
openssl req -newkey rsa:2048 -nodes \
  -keyout "${WORKDIR}/webhook.key" -out "${WORKDIR}/webhook.csr" \
  -subj "/CN=agac-e2e-webhook" >/dev/null 2>&1
cat > "${WORKDIR}/san.cnf" <<EOF
subjectAltName=IP:${HOST_IP}
EOF
openssl x509 -req -in "${WORKDIR}/webhook.csr" \
  -CA "${WORKDIR}/ca.crt" -CAkey "${WORKDIR}/ca.key" -CAcreateserial \
  -days 2 -extfile "${WORKDIR}/san.cnf" \
  -out "${WORKDIR}/webhook.crt" >/dev/null 2>&1

E2E_WEBHOOK_CA_BUNDLE="$(base64 < "${WORKDIR}/ca.crt" | tr -d '\n')"

# --- protocol tier -------------------------------------------------------
KUBECONFIG_FILE="${WORKDIR}/kubeconfig"
kind get kubeconfig --name "${CLUSTER_NAME}" > "${KUBECONFIG_FILE}"

cd "${REPO_ROOT}"
E2E_KIND=1 \
KUBECONFIG="${KUBECONFIG_FILE}" \
E2E_WEBHOOK_URL="https://${HOST_IP}:${WEBHOOK_PORT}" \
E2E_WEBHOOK_CERT="${WORKDIR}/webhook.crt" \
E2E_WEBHOOK_KEY="${WORKDIR}/webhook.key" \
E2E_WEBHOOK_CA_BUNDLE="${E2E_WEBHOOK_CA_BUNDLE}" \
E2E_KIND_NODE="${CLUSTER_NAME}-control-plane" \
python -m pytest tests/test_kind_e2e.py -v

# --- optional: image + helm chart deploy (VERDICT r1 #7) -----------------
if [ "${HELM_STAGE:-0}" = "1" ]; then
  IMAGE="aws-global-accelerator-controller:e2e"
  docker build -t "${IMAGE}" "${REPO_ROOT}"
  kind load docker-image "${IMAGE}" --name "${CLUSTER_NAME}"
  helm install agac "${REPO_ROOT}/charts/aws-global-accelerator-controller" \
    --kubeconfig "${KUBECONFIG_FILE}" \
    --set image.repository=aws-global-accelerator-controller \
    --set image.tag=e2e \
    --set image.pullPolicy=Never \
    --set webhook.enabled=false \
    --set env.AGAC_CLOUD=fake
  kubectl --kubeconfig "${KUBECONFIG_FILE}" rollout status \
    deployment/agac-aws-global-accelerator-controller --timeout=180s
  kubectl --kubeconfig "${KUBECONFIG_FILE}" apply -f config/samples/service.yaml
  # the fake-cloud controller emits GlobalAcceleratorCreated once the
  # sample Service gets an LB hostname; kind has no LB controller, so
  # just assert the deployment is healthy and leader election works
  kubectl --kubeconfig "${KUBECONFIG_FILE}" get lease \
    aws-global-accelerator-controller -o yaml
fi

echo "kind e2e tier PASSED (k8s ${K8S_VERSION})"
