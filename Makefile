# Build/test/codegen targets, the analog of the reference's Makefile
# (build/run/install/codegen/manifests, reference Makefile:19-52).

PYTHON ?= python

.PHONY: test
test:
	$(PYTHON) -m pytest tests/ -q

.PHONY: run
run:
	$(PYTHON) -m agac_tpu controller

.PHONY: webhook
webhook:
	$(PYTHON) -m agac_tpu webhook --ssl=false --port 8080

CHART_DIR := charts/aws-global-accelerator-controller

.PHONY: manifests
manifests:
	$(PYTHON) -m agac_tpu manifests -o config
	mkdir -p $(CHART_DIR)/crds
	rm -f $(CHART_DIR)/crds/*.yaml
	cp config/crd/*.yaml $(CHART_DIR)/crds/

# CI drift check: regenerating manifests must leave the tree clean
# (the analog of .github/workflows/manifests.yml); porcelain catches
# untracked/removed generated files too
.PHONY: check-manifests
check-manifests: manifests
	@test -z "$$(git status --porcelain config/ $(CHART_DIR)/crds/)" || { git status config/ $(CHART_DIR)/crds/; exit 1; }

# Opt-in full-loop e2e against REAL AWS (never in CI): needs
# credentials + E2E_LB_HOSTNAME (existing NLB/ALB DNS name), optional
# E2E_ROUTE53_HOSTNAME.  Creates one Global Accelerator and deletes it
# again (~$0.025/hr pro-rated; see tests/test_real_aws_e2e.py for the
# full contract and leak-cleanup notes).  The analog of the
# reference's local_e2e/ suite.
.PHONY: e2e-aws
e2e-aws:
	E2E_AWS=1 $(PYTHON) -m pytest tests/test_real_aws_e2e.py -q -s

# Validate the e2e-aws harness itself without credentials (fake
# backend, tight polling) — also runs as part of 'make test'
.PHONY: e2e-aws-smoke
e2e-aws-smoke:
	E2E_AWS=smoke $(PYTHON) -m pytest tests/test_real_aws_e2e.py -q

.PHONY: bench
bench:
	$(PYTHON) bench.py

.PHONY: image
image:
	docker build -t aws-global-accelerator-controller:latest .
