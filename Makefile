# Build/test/codegen targets, the analog of the reference's Makefile
# (build/run/install/codegen/manifests, reference Makefile:19-52).

PYTHON ?= python

.PHONY: test
test:
	$(PYTHON) -m pytest tests/ -q

.PHONY: run
run:
	$(PYTHON) -m agac_tpu controller

.PHONY: webhook
webhook:
	$(PYTHON) -m agac_tpu webhook --ssl=false --port 8080

CHART_DIR := charts/aws-global-accelerator-controller

.PHONY: manifests
manifests:
	$(PYTHON) -m agac_tpu manifests -o config
	mkdir -p $(CHART_DIR)/crds
	rm -f $(CHART_DIR)/crds/*.yaml
	cp config/crd/*.yaml $(CHART_DIR)/crds/

# CI drift check: regenerating manifests must leave the tree clean
# (the analog of .github/workflows/manifests.yml); porcelain catches
# untracked/removed generated files too
.PHONY: check-manifests
check-manifests: manifests
	@test -z "$$(git status --porcelain config/ $(CHART_DIR)/crds/)" || { git status config/ $(CHART_DIR)/crds/; exit 1; }

.PHONY: bench
bench:
	$(PYTHON) bench.py

.PHONY: image
image:
	docker build -t aws-global-accelerator-controller:latest .
