# Build/test/codegen targets, the analog of the reference's Makefile
# (build/run/install/codegen/manifests, reference Makefile:19-52).

PYTHON ?= python

.PHONY: test
test:
	$(PYTHON) -m pytest tests/ -q

.PHONY: run
run:
	$(PYTHON) -m agac_tpu controller

.PHONY: webhook
webhook:
	$(PYTHON) -m agac_tpu webhook --ssl=false --port 8080

CHART_DIR := charts/aws-global-accelerator-controller

.PHONY: manifests
manifests:
	$(PYTHON) -m agac_tpu manifests -o config
	mkdir -p $(CHART_DIR)/crds
	rm -f $(CHART_DIR)/crds/*.yaml
	cp config/crd/*.yaml $(CHART_DIR)/crds/

# CI drift check: regenerating manifests must leave the tree clean
# (the analog of .github/workflows/manifests.yml); porcelain catches
# untracked/removed generated files too
.PHONY: check-manifests
check-manifests: manifests
	@test -z "$$(git status --porcelain config/ $(CHART_DIR)/crds/)" || { git status config/ $(CHART_DIR)/crds/; exit 1; }

# Opt-in full-loop e2e against REAL AWS (never in CI): needs
# credentials + E2E_LB_HOSTNAME (existing NLB/ALB DNS name), optional
# E2E_ROUTE53_HOSTNAME.  Creates one Global Accelerator and deletes it
# again (~$0.025/hr pro-rated; see tests/test_real_aws_e2e.py for the
# full contract and leak-cleanup notes).  The analog of the
# reference's local_e2e/ suite.
.PHONY: e2e-aws
e2e-aws:
	E2E_AWS=1 $(PYTHON) -m pytest tests/test_real_aws_e2e.py -q -s

# Validate the e2e-aws harness itself without credentials (fake
# backend, tight polling) — also runs as part of 'make test'
.PHONY: e2e-aws-smoke
e2e-aws-smoke:
	E2E_AWS=smoke $(PYTHON) -m pytest tests/test_real_aws_e2e.py -q

# Opt-in real-apiserver e2e (the analog of the reference's kind CI
# tier, .github/workflows/e2e.yml): needs kind + docker + kubectl.
# hack/kind-e2e.sh provisions the cluster, generates webhook TLS, and
# runs tests/test_kind_e2e.py with E2E_KIND=1.  See
# KIND_E2E_RESULTS.md for recorded runs and environment caveats.
K8S_VERSION ?= 1.31.0

.PHONY: e2e-kind
e2e-kind:
	K8S_VERSION=$(K8S_VERSION) ./hack/kind-e2e.sh

# Validate the kind-tier harness itself without a cluster (in-repo
# apiserver, tight polling) — also runs as part of 'make test'
.PHONY: e2e-kind-smoke
e2e-kind-smoke:
	E2E_KIND=smoke $(PYTHON) -m pytest tests/test_kind_e2e.py -q

# Controller invariant linter (agac_tpu/analysis/): AST rules for the
# correctness classes ruff can't see — raw backend calls from
# controllers, bare lock acquire, blocking reconcile handlers, Result
# fall-throughs, module-level imports of deps CI never installs.
# Stdlib-only; CI runs it as the `invariants` job.
.PHONY: lint-invariants
lint-invariants:
	$(PYTHON) -m agac_tpu.analysis.lint agac_tpu tests bench.py

# Whole-program analyses (agac_tpu/analysis/program.py): static
# lock-order graph + inversion/bare-acquire detection, the
# shared-mutable-state census (the multi-core refactor's work list),
# the determinism audit, and the cross-process confinement analyzer
# (per-stage footprint table + picklability/escape audits).  Gates on
# REGRESSIONS only: findings in analysis_baseline.json are
# grandfathered with per-finding reasons; a non-empty UNSAFE census
# bucket, an unportable multi-core candidate stage, or a stale
# baseline entry fails.  The `timeout` pins the whole-program wall
# budget: all four analyses share one ParseCache (one parse per file),
# so blowing 120 s means the single-parse invariant regressed, not
# that the repo grew.
.PHONY: lint-program
lint-program:
	timeout 120 $(PYTHON) -m agac_tpu.analysis.program agac_tpu --report analysis_report.json --baseline analysis_baseline.json

# Regenerate the metric catalog table in docs/operations.md from the
# live registry (agac_tpu/observability/instruments.py declares every
# metric); check-metrics-catalog is the CI drift gate.
.PHONY: metrics-catalog
metrics-catalog:
	$(PYTHON) -m agac_tpu.observability.catalog docs/operations.md

.PHONY: check-metrics-catalog
check-metrics-catalog:
	$(PYTHON) -m agac_tpu.observability.catalog --check docs/operations.md

.PHONY: bench
bench:
	$(PYTHON) bench.py

.PHONY: image
image:
	docker build -t aws-global-accelerator-controller:latest .
